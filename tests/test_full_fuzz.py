"""Full-surface differential fuzz: every registered predicate + priority,
randomized clusters, strict bit-match device engine vs object-level oracle.

VERDICT r3 #10 / the reference's table scale (predicates_test.go, 3,661
lines): tests/helpers.py's generators covered resources/selectors/taints/
ports/node-affinity; this suite extends the random surface to

  - overlay/scratch storage requests vs nodes with and without overlay
    (predicates.go:576-604 fallback) and extended resources
  - direct-source volumes: GCE-PD / EBS / RBD / ISCSI / inert OTHER,
    read-only vs read-write (NoDiskConflict) and the MaxPDVolumeCount
    filters, seeded by EXISTING bound pods carrying volumes
  - preferred node affinity (NodeAffinityPriority weights)
  - container images on nodes (ImageLocalityPriority 23MB-1GB window)
  - preferAvoidPods annotations vs controller-owned pending pods
  - best-effort pods vs MemoryPressure nodes, pressure conditions
  - existing bound pods seeding capacity/ports/nonzero sums

and runs the whole DEFAULT priority battery (+ MostRequested for the
autoscaler provider) through sequential strict placement, asserting the
device engine reproduces the oracle's node choice for every pod of every
seed. PVC/PV-bound volume paths are covered separately by test_volumes.py
(they need a VolumeContext fixture); affinity in-batch dynamics by
test_affinity_fuzz.py; Policy-arg algorithms by test_policy_compat.py.
"""

from __future__ import annotations

import copy
import json
import random

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    SelectorOperator,
    SelectorRequirement,
    Toleration,
    TolerationOperator,
    Volume,
    VolumeKind,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.oracle_ext import SchedulingContext
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import AVOID_PODS_ANNOTATION
from tests.helpers import (
    LABEL_KEYS,
    LABEL_VALUES,
    TAINTS,
    Gi,
    Mi,
    random_nodes,
    random_pod,
)

IMAGES = [("nginx:1.13", 500 * Mi), ("redis:3.2", 100 * Mi),
          ("postgres:9", 1536 * Mi), ("busybox:1", 2 * Mi)]
EXT_RESOURCE = "example.com/widget"
PD_KINDS = [VolumeKind.GCE_PD, VolumeKind.AWS_EBS]
VOLUME_IDS = ["disk-a", "disk-b", "disk-c", "disk-d"]


def _random_volume(rng: random.Random) -> Volume:
    r = rng.random()
    if r < 0.35:
        return Volume(name="v", kind=rng.choice(PD_KINDS),
                      volume_id=rng.choice(VOLUME_IDS),
                      read_only=rng.random() < 0.5)
    if r < 0.5:
        return Volume(name="v", kind=VolumeKind.RBD,
                      monitors=["mon-1", "mon-2"], pool="rbd",
                      image=rng.choice(["img-a", "img-b"]))
    if r < 0.6:
        return Volume(name="v", kind=VolumeKind.ISCSI,
                      volume_id=rng.choice(["iqn-a", "iqn-b"]),
                      read_only=rng.random() < 0.5)
    return Volume(name="v", kind=VolumeKind.OTHER, volume_id="inert")


def full_random_nodes(rng: random.Random, n: int):
    nodes = random_nodes(rng, n)
    for node in nodes:
        if rng.random() < 0.4:
            node.images = [ContainerImage([name], size)
                           for name, size in rng.sample(IMAGES, 2)]
        if rng.random() < 0.3:
            node.allocatable.extended[EXT_RESOURCE] = rng.choice([2, 8])
        if rng.random() < 0.3:
            node.allocatable.storage_scratch = rng.choice([10, 50]) * Gi
            if rng.random() < 0.5:  # some nodes have NO overlay partition
                node.allocatable.storage_overlay = 20 * Gi
        if rng.random() < 0.15:
            node.annotations[AVOID_PODS_ANNOTATION] = json.dumps(
                {"preferAvoidPods": [{"podSignature": {"podController": {
                    "kind": "ReplicaSet", "uid": "rs-avoided",
                    "apiVersion": "v1"}}, "reason": "fuzz"}]})
    return nodes


def full_random_pod(rng: random.Random, i: int, node_names) -> Pod:
    pod = random_pod(rng, i, node_names)
    pod.node_name = ""  # keep every fuzz pod pending
    if rng.random() < 0.25:
        pod.volumes = [_random_volume(rng)
                       for _ in range(rng.randint(1, 2))]
    if rng.random() < 0.2:
        pod.containers[0].requests["storage.kubernetes.io/scratch"] = \
            rng.choice([1, 5]) * Gi
        if rng.random() < 0.5:
            pod.containers[0].requests["storage.kubernetes.io/overlay"] = \
                rng.choice([1, 4]) * Gi
    if rng.random() < 0.15:
        pod.containers[0].requests[EXT_RESOURCE] = rng.choice([1, 4])
    if rng.random() < 0.3:
        pod.containers[0].image = rng.choice(IMAGES)[0]
    if rng.random() < 0.2:  # preferred node affinity
        terms = [(rng.randint(1, 100), NodeSelectorTerm([
            SelectorRequirement(k, SelectorOperator.IN,
                                [rng.choice(LABEL_VALUES[k])])]))
            for k in rng.sample(LABEL_KEYS, rng.randint(1, 2))]
        if pod.affinity is None:
            pod.affinity = Affinity()
        if pod.affinity.node_affinity is None:
            pod.affinity.node_affinity = NodeAffinity()
        pod.affinity.node_affinity.preferred_terms = terms
    if rng.random() < 0.2:  # controller-owned (prefer-avoid interaction)
        pod.owner_kind = "ReplicaSet"
        pod.owner_uid = rng.choice(["rs-avoided", "rs-ordinary"])
    return pod


def _existing(rng: random.Random, nodes, n: int):
    """Bound pods seeding capacity, ports, images, and volume presence."""
    out = []
    for i in range(n):
        p = make_pod(f"bound-{i}", cpu=rng.choice([100, 500]),
                     memory=rng.choice([128 * Mi, 1 * Gi]))
        if rng.random() < 0.4:
            p.volumes = [_random_volume(rng)]
        if rng.random() < 0.2:
            p.containers[0].ports = [ContainerPort(
                host_port=rng.choice([80, 443, 8080, 9090]))]
        p.node_name = rng.choice(nodes).name
        out.append(p)
    return out


PRIORITY_SETS = [
    prio.DEFAULT_PRIORITIES,
    tuple((nm, w) for nm, w in prio.DEFAULT_PRIORITIES
          if nm != "LeastRequestedPriority") + (("MostRequestedPriority", 1),),
    (("ImageLocalityPriority", 2), ("NodeAffinityPriority", 3),
     ("EqualPriority", 1)),
]


def _oracle_sequence(nodes, existing, pending, priorities):
    infos = node_info_map(nodes, existing)
    names = sorted(infos.keys())
    rr = oracle.RoundRobin()
    ctx = SchedulingContext(infos, [])
    out = []
    for pod in pending:
        name = oracle.schedule_one(pod, names, infos, rr, priorities, ctx)
        out.append(name)
        if name is not None:
            p = copy.deepcopy(pod)
            p.node_name = name
            infos[name].add_pod(p)
            ctx.invalidate()
    return out


def _engine_sequence(nodes, existing, pending, priorities):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(copy.deepcopy(p))
    eng = SchedulingEngine(cache, priorities=priorities)
    results = eng.schedule([copy.deepcopy(p) for p in pending],
                           mode="strict")
    return [r.node_name for r in results]


@pytest.mark.parametrize("seed", list(range(12)))
def test_full_surface_strict_engine_matches_oracle(seed):
    rng = random.Random(1000 + seed)
    nodes = full_random_nodes(rng, rng.choice([8, 16]))
    existing = _existing(rng, nodes, rng.randint(4, 12))
    names = [n.name for n in nodes]
    pending = [full_random_pod(rng, i, names)
               for i in range(rng.choice([16, 24]))]
    pset = PRIORITY_SETS[seed % len(PRIORITY_SETS)]
    want = _oracle_sequence(nodes, existing, pending, pset)
    got = _engine_sequence(nodes, existing, pending, pset)
    assert got == want, (
        f"seed {seed}: first divergence at "
        f"{next(i for i, (g, w) in enumerate(zip(got, want)) if g != w)}")


@pytest.mark.parametrize("seed", [0, 5])
def test_full_surface_feature_coverage(seed):
    """The generator genuinely exercises every axis (a fuzz suite whose
    random draws silently stopped producing a feature tests nothing)."""
    rng = random.Random(1000 + seed)
    nodes = full_random_nodes(rng, 16)
    pending = [full_random_pod(rng, i, [n.name for n in nodes])
               for i in range(64)]
    assert any(n.images for n in nodes)
    assert any(EXT_RESOURCE in n.allocatable.extended for n in nodes)
    assert any(AVOID_PODS_ANNOTATION in n.annotations for n in nodes)
    assert any(n.allocatable.storage_scratch for n in nodes)
    assert any(p.volumes for p in pending)
    assert any("storage.kubernetes.io/scratch" in p.containers[0].requests
               for p in pending)
    assert any(p.affinity and p.affinity.node_affinity
               and p.affinity.node_affinity.preferred_terms
               for p in pending)
    assert any(p.owner_uid == "rs-avoided" for p in pending)
    assert any(p.containers[0].ports for p in pending)
    assert any(p.tolerations for p in pending)


@pytest.mark.parametrize("seed", [1, 4, 9])
def test_full_surface_wave_mode_placements_are_valid(seed):
    """Wave mode may order ties differently (documented batch semantics),
    but every placement must still satisfy the hard predicates: capacity
    never oversubscribed, pod counts respected, host ports never collide,
    and volumes never conflict (NoDiskConflict over the co-located set)."""
    rng = random.Random(2000 + seed)
    nodes = full_random_nodes(rng, 12)
    existing = _existing(rng, nodes, 8)
    names = [n.name for n in nodes]
    pending = [full_random_pod(rng, i, names) for i in range(32)]

    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(copy.deepcopy(p))
    eng = SchedulingEngine(cache, priorities=prio.DEFAULT_PRIORITIES)
    results = eng.schedule([copy.deepcopy(p) for p in pending], mode="wave")

    by_node = {}
    for r in results:
        if r.node_name is not None:
            by_node.setdefault(r.node_name, []).append(r.pod)
    node_by_name = {n.name: n for n in nodes}
    for nm, pods in by_node.items():
        node = node_by_name[nm]
        prior = [p for p in existing if p.node_name == nm]
        cpu = sum(p.resource_request().milli_cpu for p in pods + prior)
        mem = sum(p.resource_request().memory for p in pods + prior)
        assert cpu <= node.allocatable.milli_cpu, f"{nm} cpu oversubscribed"
        assert mem <= node.allocatable.memory, f"{nm} mem oversubscribed"
        assert len(pods) + len(prior) <= node.allowed_pod_number
        ports = [pt.host_port for p in pods + prior
                 for pt in p.containers[0].ports if pt.host_port]
        assert len(ports) == len(set(ports)), f"{nm} port collision"
        # NoDiskConflict: two CO-LOCATED pods sharing a conflict key must
        # both mount it read-only (predicates.go:128-177; a pod never
        # conflicts with itself)
        from kubernetes_tpu.state.volumes import pod_conflict_keys
        per_pod = []
        for p in pods + prior:
            keys = {}
            for key, ro in pod_conflict_keys(p):
                keys[key] = keys.get(key, True) and ro
            per_pod.append(keys)
        for i, ka in enumerate(per_pod):
            for kb in per_pod[i + 1:]:
                for key in set(ka) & set(kb):
                    assert ka[key] and kb[key], \
                        f"{nm}: volume conflict on {key}"


@pytest.mark.parametrize("seed", [2, 4])
def test_max_pd_volume_reject_branch_exercised(seed, monkeypatch):
    """Regression (review): with the default 39/16 limits and only 4
    distinct volume ids, the MaxPDVolumeCount reject branch can never fire.
    Pin KUBE_MAX_PD_VOLS=2 so clusters actually hit the ceiling, and
    bit-match engine vs oracle through it (defaults.go:233 getMaxVols)."""
    monkeypatch.setenv("KUBE_MAX_PD_VOLS", "2")
    rng = random.Random(3000 + seed)
    nodes = full_random_nodes(rng, 6)
    existing = _existing(rng, nodes, 10)
    # make PD volumes common so per-node distinct ids exceed the limit
    pending = []
    for i in range(24):
        p = full_random_pod(rng, i, [n.name for n in nodes])
        if rng.random() < 0.7:
            p.volumes = [Volume(name="v", kind=rng.choice(PD_KINDS),
                                volume_id=rng.choice(VOLUME_IDS))]
        pending.append(p)
    pset = prio.DEFAULT_PRIORITIES
    want = _oracle_sequence(nodes, existing, pending, pset)
    got = _engine_sequence(nodes, existing, pending, pset)
    assert got == want
    # the ceiling genuinely bites: against the FINAL state (existing +
    # placed pending), some PD pod is rejected by some node's filter
    from kubernetes_tpu.ops.oracle_volumes import max_pd_volume_count
    from kubernetes_tpu.state.volumes import EMPTY_VOLUME_CONTEXT
    placed = []
    for p, nm in zip(pending, want):
        if nm is not None:
            q = copy.deepcopy(p)
            q.node_name = nm
            placed.append(q)
    infos = node_info_map(nodes, existing + placed)
    rejected = any(
        not all(max_pd_volume_count(p, info, EMPTY_VOLUME_CONTEXT))
        for p in pending if p.volumes for info in infos.values())
    assert rejected, "generator failed to exercise the reject branch"
