"""ktadm (kubeadm analog): init phases, token join, preflight, reset.

Reference: cmd/kubeadm/app/{phases,preflight,discovery}. Pinned here:
- init runs preflight -> certs -> kubeconfig -> control-plane manifests
  -> bootstrap-token and yields a working authenticated control plane.
- the join flow is the TLS bootstrap: token auth -> CSR -> auto-approve
  -> sign -> register node with the issued identity; a wrong CA hash
  aborts (discovery token pinning), a bad token is Unauthenticated.
- the static manifests are loadable by the hollow kubelet's file source
  (what kubeadm's /etc/kubernetes/manifests is to the real kubelet).
- bootstrap tokens expire and can be listed/created/deleted.
"""

import io
import json
import os

import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.auth.authn import Credential, Unauthenticated
from kubernetes_tpu.auth.authz import Forbidden
from kubernetes_tpu.cli.ktadm import KtAdm, ca_hash, generate_token


def init_cluster(tmp_path, now=None):
    out = io.StringIO()
    adm = KtAdm(out=out, **({"now": now} if now else {}))
    res = adm.init(str(tmp_path / "kt"))
    return adm, res, out


def test_init_phases_and_artifacts(tmp_path):
    adm, res, out = init_cluster(tmp_path)
    wd = res.workdir
    assert os.path.exists(os.path.join(wd, "pki", "ca.key"))
    for comp in ("admin", "controller-manager", "scheduler"):
        assert os.path.exists(os.path.join(wd, comp + ".conf"))
    manifests = sorted(os.listdir(os.path.join(wd, "manifests")))
    assert manifests == ["kube-apiserver.json",
                        "kube-controller-manager.json",
                        "kube-scheduler.json"]
    assert "initialized successfully" in out.getvalue()
    # the admin credential really is cluster-admin through the chain
    res.api.create("Namespace", __import__(
        "kubernetes_tpu.api.workloads", fromlist=["Namespace"]
    ).Namespace("prod"), cred=res.admin_cred)
    # an anonymous request is rejected
    with pytest.raises(Unauthenticated):
        res.api.list("Pod", cred=None)


def test_preflight_rejects_second_init(tmp_path):
    adm, res, _ = init_cluster(tmp_path)
    adm2 = KtAdm(out=io.StringIO())
    with pytest.raises(SystemExit):
        adm2.init(res.workdir)
    # reset clears the artifacts; init works again
    adm2.reset(res.workdir)
    adm2.init(res.workdir)


def test_token_join_flow(tmp_path):
    adm, res, _ = init_cluster(tmp_path)
    node_cred = adm.join(res, "worker-1", res.token,
                         ca_cert_hash=ca_hash(res.ca_key))
    node = res.api.get("Node", "", "worker-1", cred=res.admin_cred)
    assert node.name == "worker-1"
    # the issued identity is the node's own (system:node:worker-1) —
    # NodeRestriction-scoped, not admin: it cannot delete other nodes
    res.api.list("Node", cred=node_cred)
    with pytest.raises(Forbidden):
        res.api.create("Namespace", __import__(
            "kubernetes_tpu.api.workloads", fromlist=["Namespace"]
        ).Namespace("x"), cred=node_cred)


def test_join_rejects_bad_token_and_bad_ca_hash(tmp_path):
    adm, res, _ = init_cluster(tmp_path)
    with pytest.raises(Unauthenticated):
        adm.join(res, "w", "aaaaaa.bbbbbbbbbbbbbbbb")
    with pytest.raises(SystemExit, match="MITM"):
        adm.join(res, "w", res.token, ca_cert_hash="sha256:deadbeef")


def test_token_expiry_and_lifecycle(tmp_path):
    t = [2_000_000_000.0]
    adm, res, out = init_cluster(tmp_path, now=lambda: t[0])
    assert adm.token_list(res)  # the init token
    tok2 = adm.token_create(res, ttl=60.0)
    assert len(adm.token_list(res)) == 2
    # expiry: advance past ttl; the token no longer authenticates
    t[0] += 3600.0
    with pytest.raises(Unauthenticated):
        adm.join(res, "w", tok2)
    # delete the init token
    tid = res.token.split(".")[0]
    adm.token_delete(res, tid)
    with pytest.raises(SystemExit):
        adm.token_delete(res, tid)


def test_static_manifests_feed_kubelet_file_source(tmp_path):
    from kubernetes_tpu.api.types import make_node
    from kubernetes_tpu.nodes.kubelet import HollowKubelet
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    adm, res, _ = init_cluster(tmp_path)
    api = ApiServerLite()
    node = make_node("cp-1", cpu=8000, memory=1 << 34)
    api.create("Node", node)
    kubelet = HollowKubelet(api, node)
    n = kubelet.load_static_dir(os.path.join(res.workdir, "manifests"))
    assert n == 3
    kubelet.workers.drain()
    # mirror pods surfaced on the apiserver
    mirrors = [p for p in api.list("Pod")[0]
               if p.namespace == "kube-system"]
    assert {p.name for p in mirrors} == {
        "kube-apiserver", "kube-controller-manager", "kube-scheduler"}


def test_generate_token_format():
    tok = generate_token()
    tid, _, sec = tok.partition(".")
    assert len(tid) == 6 and len(sec) == 16
    assert tok == tok.lower()


# ------------------------------------------------- printers (pkg/printers)


def _cli_with_nodes():
    import io

    from kubernetes_tpu.api.types import make_node
    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.server.apiserver import ApiServer
    from kubernetes_tpu.api.workloads import Namespace

    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    for name, cpu in (("n-b", 2000), ("n-a", 4000), ("n-c", 1000)):
        api.store.create("Node", make_node(name, cpu=cpu, memory=1 << 31))
    out = io.StringIO()
    return Ktctl(api, out=out), out


def test_custom_columns_output():
    kt, out = _cli_with_nodes()
    assert kt.run(["get", "nodes", "-o",
                   "custom-columns=NAME:.name,CPU:.allocatable.milli_cpu"
                   ]) == 0
    text = out.getvalue()
    lines = text.strip().splitlines()
    assert lines[0].split() == ["NAME", "CPU"]
    assert any(ln.split() == ["n-a", "4000"] for ln in lines)


def test_jsonpath_output():
    kt, out = _cli_with_nodes()
    assert kt.run(["get", "nodes", "-o",
                   "jsonpath={.items[*].name}"]) == 0
    assert set(out.getvalue().split()) == {"n-a", "n-b", "n-c"}
    out.truncate(0), out.seek(0)
    assert kt.run(["get", "nodes", "-o",
                   "jsonpath={.items[0].allocatable.milli_cpu}"]) == 0
    assert out.getvalue().strip() in {"1000", "2000", "4000"}


def test_sort_by_orders_rows():
    kt, out = _cli_with_nodes()
    assert kt.run(["get", "nodes", "--sort-by",
                   "{.allocatable.milli_cpu}", "-o",
                   "custom-columns=NAME:.name"]) == 0
    names = [ln.strip() for ln in out.getvalue().strip().splitlines()[1:]]
    assert names == ["n-c", "n-b", "n-a"]


def test_ktctl_with_admin_kubeconfig_against_secure_cluster(tmp_path):
    from kubernetes_tpu.cli.ktctl import Ktctl

    adm, res, _ = init_cluster(tmp_path)
    adm.join(res, "worker-1", res.token)
    out = io.StringIO()
    # kubeconfig written by phase_kubeconfig carries the admin identity
    kt = Ktctl(res.api, out=out,
               kubeconfig=os.path.join(res.workdir, "admin.conf"))
    assert kt.run(["get", "nodes"]) == 0
    assert "worker-1" in out.getvalue()
    # a credential-less ktctl against the same secure cluster fails closed
    kt_anon = Ktctl(res.api, out=io.StringIO())
    with pytest.raises(Unauthenticated):
        kt_anon.run(["get", "nodes"])


def test_sort_by_numeric_not_lexicographic():
    import io

    from kubernetes_tpu.api.types import make_node
    from kubernetes_tpu.api.workloads import Namespace
    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.server.apiserver import ApiServer

    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    # 900 vs 1000: lexicographic would put "1000" first
    for name, cpu in (("big", 1000), ("small", 900)):
        api.store.create("Node", make_node(name, cpu=cpu, memory=1 << 31))
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    assert kt.run(["get", "nodes", "--sort-by",
                   "{.allocatable.milli_cpu}", "-o",
                   "custom-columns=NAME:.name"]) == 0
    names = [ln.strip() for ln in out.getvalue().strip().splitlines()[1:]]
    assert names == ["small", "big"]


def test_unsupported_jsonpath_fails_cleanly():
    kt, out = _cli_with_nodes()
    # filter expressions are outside the subset: clean error, rc=1
    assert kt.run(["get", "nodes", "-o",
                   "jsonpath={.items[?(@.ready)].name}"]) == 1
    assert "unsupported jsonpath" in out.getvalue()


def test_kubeconfig_with_rest_client_does_not_crash(tmp_path):
    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.cli.rest_client import RestClient
    from kubernetes_tpu.server.rest_http import RestServer

    adm, res, _ = init_cluster(tmp_path)
    # RestClient authenticates at the transport; the kubeconfig cred must
    # NOT be partial-applied onto its verbs (they take no cred kwarg)
    srv = RestServer(res.api)
    srv.start()
    try:
        client = RestClient(f"http://127.0.0.1:{srv.port}")
        kt = Ktctl(client, out=io.StringIO(),
                   kubeconfig=os.path.join(res.workdir, "admin.conf"))
        # auth=True without a transport token -> the 401 surfaces as a
        # clean CLI error (rc=1), never a TypeError from cred kwargs
        out = io.StringIO()
        kt.out = out
        assert kt.run(["get", "nodes"]) == 1
        assert "401" in out.getvalue()
    finally:
        srv.stop()
