"""Golden tests: TPU priority kernels vs the pure-Python oracle (integer
score semantics per least_requested.go / balanced_resource_allocation.go /
most_requested.go / taint_toleration.go)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.predicates import node_arrays, pod_arrays
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch
from tests.helpers import Gi, Mi, random_nodes, random_pod


def build(pods, nodes, bound=()):
    infos = node_info_map(nodes, list(bound))
    snap = ClusterSnapshot()
    snap.refresh(infos)
    batch = PodBatch(pods, snap)
    return pod_arrays(batch), node_arrays(snap), snap, infos


PRIORITY_SETS = [
    (("LeastRequestedPriority", 1),),
    (("MostRequestedPriority", 1),),
    (("BalancedResourceAllocation", 1),),
    (("LeastRequestedPriority", 1), ("BalancedResourceAllocation", 1),
     ("TaintTolerationPriority", 1)),
]


@pytest.mark.parametrize("seed", [0, 7])
@pytest.mark.parametrize("pset", PRIORITY_SETS)
def test_scores_match_oracle(seed, pset):
    rng = random.Random(seed)
    nodes = random_nodes(rng, 16)
    names = [n.name for n in nodes]
    pending = [random_pod(rng, i, names) for i in range(25)]
    bound = []
    for i in range(20):
        p = random_pod(rng, 500 + i, names)
        p.node_name = rng.choice(names)
        bound.append(p)
    parrs, narrs, snap, infos = build(pending, nodes, bound)
    got = np.asarray(prio.score(parrs, narrs, pset))
    n_real = len(snap.node_names)
    for pi, pod in enumerate(pending):
        ordered = [infos[nm] for nm in snap.node_names]
        want = oracle.prioritize(pod, ordered, pset)
        np.testing.assert_array_equal(
            got[pi, :n_real], want,
            err_msg=f"pod {pod.name} priorities {pset}")


def test_least_requested_exact_values():
    # cap 4000m/32Gi; existing nonzero request 1000m/8Gi; pod 1000m/8Gi
    node = make_node("n", cpu=4000, memory=32 * Gi)
    holder = make_pod("h", cpu=1000, memory=8 * Gi, node_name="n")
    pod = make_pod("p", cpu=1000, memory=8 * Gi)
    parrs, narrs, snap, infos = build([pod], [node], [holder])
    got = int(np.asarray(prio.score(parrs, narrs, (("LeastRequestedPriority", 1),)))[0, 0])
    # cpu: (4000-2000)*10/4000 = 5 ; mem: (32-16)*10/32 = 5 ; avg = 5
    assert got == 5
    assert got == oracle.least_requested_score(pod, infos["n"])


def test_least_requested_default_requests():
    # unset requests count as 100m / 200Mi for scoring only
    node = make_node("n", cpu=1000, memory=2000 * Mi)
    pod = make_pod("p")  # no explicit requests
    parrs, narrs, snap, infos = build([pod], [node])
    got = int(np.asarray(prio.score(parrs, narrs, (("LeastRequestedPriority", 1),)))[0, 0])
    # cpu: (1000-100)*10/1000 = 9 ; mem: (2000-200)*10/2000 = 9
    assert got == 9


def test_balanced_allocation_perfect_balance():
    node = make_node("n", cpu=4000, memory=32 * Gi)
    pod = make_pod("p", cpu=2000, memory=16 * Gi)  # both fractions = 0.5
    parrs, narrs, snap, infos = build([pod], [node])
    got = int(np.asarray(prio.score(parrs, narrs, (("BalancedResourceAllocation", 1),)))[0, 0])
    assert got == 10
    assert got == oracle.balanced_allocation_score(pod, infos["n"])


def test_balanced_allocation_overcommit_scores_zero():
    node = make_node("n", cpu=1000, memory=1 * Gi)
    pod = make_pod("p", cpu=2000, memory=128 * Mi)
    parrs, narrs, snap, infos = build([pod], [node])
    got = int(np.asarray(prio.score(parrs, narrs, (("BalancedResourceAllocation", 1),)))[0, 0])
    assert got == 0


def test_taint_toleration_normalized():
    from kubernetes_tpu.api.types import Taint, TaintEffect
    n0 = make_node("n0")
    n1 = make_node("n1", taints=[Taint("noisy", "", TaintEffect.PREFER_NO_SCHEDULE)])
    n2 = make_node("n2", taints=[
        Taint("noisy", "", TaintEffect.PREFER_NO_SCHEDULE),
        Taint("louder", "", TaintEffect.PREFER_NO_SCHEDULE)])
    pod = make_pod("p")
    parrs, narrs, snap, infos = build([pod], [n0, n1, n2])
    got = np.asarray(prio.score(parrs, narrs, (("TaintTolerationPriority", 1),)))[0]
    ordered = [infos[nm] for nm in snap.node_names]
    want = oracle.taint_toleration_scores(pod, ordered)
    assert list(got[: len(want)]) == want  # n0:10 n1:5 n2:0
