"""CRD serving (apiextensions-apiserver analog) + the aggregator
(kube-aggregator analog).

Reference behaviors pinned here:
- CRD naming rule name == "<plural>.<group>" and NamesAccepted/Established
  conditions (apiextensions-apiserver/pkg/apis/apiextensions/validation,
  pkg/controller/{naming,establish}).
- dynamic registry: an Established CRD's kind is served through the full
  handler chain, unknown kinds 404
  (apiextensions-apiserver/pkg/apiserver/customresource_handler.go).
- customresourcecleanup finalizer: deleting a CRD purges its instances.
- APIService routing + availability gating (kube-aggregator/pkg/
  controllers/status/available_controller.go).
"""

import pytest

from kubernetes_tpu.api.extensions import (
    APIService,
    CRDNames,
    CustomResource,
    CustomResourceDefinition,
    ServiceReference,
)
from kubernetes_tpu.api.rbac import (
    PolicyRule,
    Role,
    RoleBinding,
    RoleRef,
    Subject,
    UserInfo,
)
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.auth.authn import Credential, TokenAuthenticator, \
    UnionAuthenticator
from kubernetes_tpu.auth.authz import Forbidden
from kubernetes_tpu.server.apiserver import ApiServer, Invalid
from kubernetes_tpu.server.apiserver_lite import NotFound
from kubernetes_tpu.server.extensions import Aggregator, Unavailable


def make_crd(**over):
    kw = dict(
        name="tputopologies.sched.example.io",
        group="sched.example.io",
        version="v1",
        names=CRDNames(plural="tputopologies", kind="TpuTopology"),
        validation={
            "required": ["chips"],
            "chips": {"type": "integer", "minimum": 1, "maximum": 4096},
            "generation": {"type": "string",
                           "enum": ["v4", "v5e", "v5p"]},
        },
    )
    kw.update(over)
    return CustomResourceDefinition(**kw)


def make_server():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    return api


# ---------------------------------------------------------------- lifecycle


def test_crd_create_establishes_and_serves():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    crd = api.get("CustomResourceDefinition", "",
                  "tputopologies.sched.example.io")
    assert crd.names_accepted and crd.established
    api.create("TpuTopology", CustomResource(
        "TpuTopology", "pod-a", namespace="default",
        spec={"chips": 256, "generation": "v5e"}))
    got = api.get("TpuTopology", "default", "pod-a")
    assert got.spec["chips"] == 256
    objs, _ = api.list("TpuTopology")
    assert [o.name for o in objs] == ["pod-a"]


def test_crd_name_must_be_plural_dot_group():
    api = make_server()
    with pytest.raises(Invalid):
        api.create("CustomResourceDefinition",
                   make_crd(name="topologies.sched.example.io"))
    with pytest.raises(Invalid):
        api.create("CustomResourceDefinition",
                   make_crd(name="tputopologies.sched", group="sched"))


def test_unknown_kind_404s_everywhere():
    api = make_server()
    with pytest.raises(NotFound):
        api.create("TpuTopology", CustomResource(
            "TpuTopology", "x", namespace="default", spec={"chips": 1}))
    with pytest.raises(NotFound):
        api.list("TpuTopology")
    with pytest.raises(NotFound):
        api.get("TpuTopology", "default", "x")
    with pytest.raises(NotFound):
        api.delete("TpuTopology", "default", "x")


def test_name_conflict_with_builtin_not_accepted_and_not_served():
    api = make_server()
    bad = CustomResourceDefinition(
        name="pods.fake.example.io", group="fake.example.io", version="v1",
        names=CRDNames(plural="pods", kind="Pod"))
    api.create("CustomResourceDefinition", bad)
    stored = api.get("CustomResourceDefinition", "", "pods.fake.example.io")
    assert not stored.names_accepted and not stored.established
    # the conflicting kind resolves to the BUILT-IN resource, and the CRD
    # plural conflict means no custom serving was added
    cond = stored.condition("NamesAccepted")
    assert "already in use" in cond.message


def test_name_conflict_between_crds():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    second = CustomResourceDefinition(
        name="tputopologies.other.example.io", group="other.example.io",
        version="v1",
        names=CRDNames(plural="tputopologies", kind="TpuTopology2"))
    api.create("CustomResourceDefinition", second)
    stored = api.get("CustomResourceDefinition", "",
                     "tputopologies.other.example.io")
    assert not stored.names_accepted


def test_crd_delete_cascades_instances():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    for i in range(3):
        api.create("TpuTopology", CustomResource(
            "TpuTopology", f"t{i}", namespace="default",
            spec={"chips": 8}))
    api.delete("CustomResourceDefinition", "",
               "tputopologies.sched.example.io")
    with pytest.raises(NotFound):
        api.get("CustomResourceDefinition", "",
                "tputopologies.sched.example.io")
    # kind no longer served, instances gone from the raw store too
    with pytest.raises(NotFound):
        api.list("TpuTopology")
    assert api.store.list("TpuTopology")[0] == []


# --------------------------------------------------------------- validation


def test_schema_validation_rejects():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())

    def cr(spec):
        return CustomResource("TpuTopology", "bad", namespace="default",
                              spec=spec)

    with pytest.raises(Invalid):  # missing required
        api.create("TpuTopology", cr({}))
    with pytest.raises(Invalid):  # wrong type
        api.create("TpuTopology", cr({"chips": "many"}))
    with pytest.raises(Invalid):  # below minimum
        api.create("TpuTopology", cr({"chips": 0}))
    with pytest.raises(Invalid):  # above maximum
        api.create("TpuTopology", cr({"chips": 8192}))
    with pytest.raises(Invalid):  # enum violation
        api.create("TpuTopology", cr({"chips": 8, "generation": "v3"}))
    with pytest.raises(Invalid):  # bool is not an integer
        api.create("TpuTopology", cr({"chips": True}))
    # update path validates too
    api.create("TpuTopology", cr({"chips": 8}))
    broken = CustomResource("TpuTopology", "bad", namespace="default",
                            spec={"chips": -1})
    with pytest.raises(Invalid):
        api.update("TpuTopology", broken)


def test_scope_enforced():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    with pytest.raises(Invalid):  # namespaced CRD, no namespace
        api.create("TpuTopology",
                   CustomResource("TpuTopology", "x", spec={"chips": 1}))
    api.create("CustomResourceDefinition", CustomResourceDefinition(
        name="meshes.sched.example.io", group="sched.example.io",
        version="v1", names=CRDNames(plural="meshes", kind="Mesh"),
        scope="Cluster"))
    with pytest.raises(Invalid):  # cluster-scoped CRD, namespace set
        api.create("Mesh", CustomResource("Mesh", "m", namespace="default"))
    api.create("Mesh", CustomResource("Mesh", "m"))
    assert api.get("Mesh", "", "m").name == "m"


# --------------------------------------------------------------------- rbac


def test_rbac_over_custom_resources():
    authn = UnionAuthenticator([TokenAuthenticator({
        "admin": UserInfo("root", groups=["system:masters"]),
        "dev": UserInfo("dev-user")})])
    api = ApiServer(auth=True, authenticator=authn)
    api.store.create("Namespace", Namespace("default"))
    api.bootstrap_rbac()
    admin, dev = Credential(token="admin"), Credential(token="dev")
    api.create("CustomResourceDefinition", make_crd(), cred=admin)
    api.store.create("Role", Role("topo-reader", "default", rules=[
        PolicyRule(verbs=["get", "list"], resources=["tputopologies"])]))
    api.store.create("RoleBinding", RoleBinding(
        "read-topos", "default", subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "topo-reader")))
    api.create("TpuTopology", CustomResource(
        "TpuTopology", "t", namespace="default", spec={"chips": 4}),
        cred=admin)
    # reader can read via the CRD's plural, cannot write
    assert api.get("TpuTopology", "default", "t", cred=dev).spec["chips"] == 4
    with pytest.raises(Forbidden):
        api.create("TpuTopology", CustomResource(
            "TpuTopology", "t2", namespace="default", spec={"chips": 4}),
            cred=dev)


# ---------------------------------------------------------------- discovery


def test_discovery_lists_builtins_and_crds():
    api = make_server()
    doc = api.discovery()
    names = {(r["kind"], r["name"]) for r in doc["resources"]}
    assert ("Pod", "pods") in names and ("Node", "nodes") in names
    assert not any(r["kind"] == "TpuTopology" for r in doc["resources"])
    api.create("CustomResourceDefinition", make_crd())
    doc = api.discovery()
    custom = [r for r in doc["resources"] if r["kind"] == "TpuTopology"]
    assert custom and custom[0]["group"] == "sched.example.io"
    assert custom[0]["namespaced"]


# --------------------------------------------------------------- aggregator


def make_backend():
    """An in-process extension apiserver (sample-apiserver shape): a second
    ApiServer serving a CRD-defined kind of its own."""
    backend = ApiServer()
    backend.store.create("Namespace", Namespace("default"))
    backend.create("CustomResourceDefinition", CustomResourceDefinition(
        name="nodemetrics.metrics.example.io", group="metrics.example.io",
        version="v1",
        names=CRDNames(plural="nodemetrics", kind="NodeMetrics"),
        scope="Cluster"))
    backend.create("NodeMetrics",
                   CustomResource("NodeMetrics", "n1", spec={"cpu": 2}))
    return backend


def test_aggregator_routes_remote_group():
    primary = make_server()
    agg = Aggregator(primary)
    backend = make_backend()
    agg.register_backend(APIService(
        name="v1.metrics.example.io", group="metrics.example.io",
        version="v1", service=ServiceReference("kube-system", "metrics")),
        backend=backend)
    objs, _ = agg.handle("metrics.example.io", "v1", "list", "NodeMetrics")
    assert [o.name for o in objs] == ["n1"]
    # core group falls through to the primary
    primary.store.create("Namespace", Namespace("kube-system"))
    objs, _ = agg.handle("", "v1", "list", "Namespace")
    assert {o.name for o in objs} == {"default", "kube-system"}


def test_aggregator_unavailable_backend_503s():
    primary = make_server()
    agg = Aggregator(primary, probe_interval=0.0)
    backend = make_backend()
    svc = APIService(
        name="v1.metrics.example.io", group="metrics.example.io",
        version="v1", service=ServiceReference("kube-system", "metrics"))
    agg.register_backend(svc, backend=backend)
    assert primary.store.get("APIService", "",
                             "v1.metrics.example.io").available
    # break the backend's healthz; the availability pass flips the row
    backend.healthz = lambda: {"status": "failed"}
    with pytest.raises(Unavailable):
        agg.handle("metrics.example.io", "v1", "list", "NodeMetrics")
    row = primary.store.get("APIService", "", "v1.metrics.example.io")
    assert not row.available
    # recovery: healthz back up -> traffic resumes
    backend.healthz = lambda: {"status": "ok"}
    objs, _ = agg.handle("metrics.example.io", "v1", "list", "NodeMetrics")
    assert len(objs) == 1


def test_aggregator_local_apiservice_and_discovery():
    primary = make_server()
    agg = Aggregator(primary)
    agg.register_backend(APIService(
        name="v1.sched.example.io", group="sched.example.io", version="v1"))
    primary.create("CustomResourceDefinition", make_crd())
    primary.create("TpuTopology", CustomResource(
        "TpuTopology", "t", namespace="default", spec={"chips": 2}))
    objs, _ = agg.handle("sched.example.io", "v1", "list", "TpuTopology")
    assert [o.name for o in objs] == ["t"]
    doc = agg.discovery()
    groups = {(g["group"], g["local"], g["available"])
              for g in doc["aggregatedGroups"]}
    assert ("sched.example.io", True, True) in groups


# ----------------------------------------------------- REST + CLI end-to-end


def test_crd_over_rest_group_path():
    import pytest as _pytest
    from kubernetes_tpu.cli.rest_client import RestClient
    from kubernetes_tpu.server.rest_http import RestServer

    api = make_server()
    srv = RestServer(api)
    srv.start()
    try:
        client = RestClient(f"http://127.0.0.1:{srv.port}")
        client.create("CustomResourceDefinition", make_crd())
        # the discovery doc now advertises the group resource
        doc = client.discovery()
        assert any(r["kind"] == "TpuTopology" and
                   r["group"] == "sched.example.io"
                   for r in doc["resources"])
        # CRUD rides /apis/sched.example.io/v1/namespaces/default/...
        client.create("TpuTopology", CustomResource(
            "TpuTopology", "ring0", namespace="default",
            spec={"chips": 64, "generation": "v5p"}))
        got = client.get("TpuTopology", "default", "ring0")
        assert got.spec == {"chips": 64, "generation": "v5p"}
        objs, _ = client.list("TpuTopology")
        assert [o.name for o in objs] == ["ring0"]
        client.delete("TpuTopology", "default", "ring0")
        with pytest.raises(NotFound):
            client.get("TpuTopology", "default", "ring0")
        # schema violations surface as HTTP errors, not silent accepts
        from kubernetes_tpu.cli.rest_client import HttpError
        with _pytest.raises(HttpError):
            client.create("TpuTopology", CustomResource(
                "TpuTopology", "bad", namespace="default",
                spec={"chips": 0}))
    finally:
        srv.stop()


def test_ktctl_crd_workflow(tmp_path):
    import io

    from kubernetes_tpu.cli.ktctl import Ktctl

    api = make_server()
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    # apply an upstream-shaped CRD manifest (apiextensions.k8s.io v1.7 era)
    manifest = tmp_path / "crd.yaml"
    manifest.write_text("""
apiVersion: apiextensions.k8s.io/v1beta1
kind: CustomResourceDefinition
metadata:
  name: tputopologies.sched.example.io
spec:
  group: sched.example.io
  version: v1
  scope: Namespaced
  names:
    plural: tputopologies
    kind: TpuTopology
    shortNames: [tt]
  validation:
    openAPIV3Schema:
      properties:
        spec:
          required: [chips]
          properties:
            chips: {type: integer, minimum: 1}
---
apiVersion: sched.example.io/v1
kind: TpuTopology
metadata:
  name: ring0
  namespace: default
spec:
  chips: 128
""")
    assert kt.run(["create", "-f", str(manifest)]) == 0
    assert kt.run(["get", "tputopologies", "-n", "default"]) == 0
    assert "ring0" in out.getvalue()
    # short-name resolution via discovery
    assert kt.run(["get", "tt", "ring0", "-n", "default",
                   "-o", "json"]) == 0
    assert '"chips": 128' in out.getvalue()
    # api-resources lists the custom group
    assert kt.run(["api-resources"]) == 0
    assert "sched.example.io" in out.getvalue()
    # delete through the CLI
    assert kt.run(["delete", "tputopologies", "ring0", "-n", "default"]) == 0
    assert kt.run(["get", "tputopologies", "-n", "default"]) == 0


# ----------------------------------------------- review-finding regressions


def test_bounded_field_with_nonnumeric_value_422s_not_500s():
    api = make_server()
    api.create("CustomResourceDefinition", CustomResourceDefinition(
        name="widgets.w.example.io", group="w.example.io", version="v1",
        names=CRDNames(plural="widgets", kind="Widget"),
        validation={"replicas": {"minimum": 0}}))  # bounds, no "type"
    with pytest.raises(Invalid):
        api.create("Widget", CustomResource(
            "Widget", "w", namespace="default",
            spec={"replicas": "three"}))


def test_crd_update_revalidates_names():
    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    crd = api.get("CustomResourceDefinition", "",
                  "tputopologies.sched.example.io")
    # a PUT that renames the kind into a builtin collision is stored
    # not-accepted and the custom kind stops being served
    crd.names.kind = "Pod"
    api.update("CustomResourceDefinition", crd)
    stored = api.get("CustomResourceDefinition", "",
                     "tputopologies.sched.example.io")
    assert not stored.names_accepted and not stored.established
    with pytest.raises(NotFound):
        api.create("TpuTopology", CustomResource(
            "TpuTopology", "x", namespace="default", spec={"chips": 1}))


def test_delete_missing_crd_raises_not_found():
    api = make_server()
    with pytest.raises(NotFound):
        api.delete("CustomResourceDefinition", "", "nope.example.io")


def test_ktctl_prints_real_plural_for_custom_kinds():
    import io

    from kubernetes_tpu.cli.ktctl import Ktctl

    api = make_server()
    api.create("CustomResourceDefinition", make_crd())
    api.create("TpuTopology", CustomResource(
        "TpuTopology", "ring0", namespace="default", spec={"chips": 8}))
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    assert kt.run(["get", "tputopologies", "-n", "default",
                   "-o", "name"]) == 0
    assert "tputopologies/ring0" in out.getvalue()
    assert "tputopologys" not in out.getvalue()


def test_crd_watch_over_rest():
    """Finding regression: watching a CRD kind over REST resolves through
    discovery (not silently widened to all built-in kinds)."""
    import threading
    import time as _time

    from kubernetes_tpu.cli.rest_client import RestClient
    from kubernetes_tpu.server.rest_http import RestServer

    api = make_server()
    srv = RestServer(api)
    srv.start()
    try:
        client = RestClient(f"http://127.0.0.1:{srv.port}")
        client.create("CustomResourceDefinition", make_crd())
        rv = client.list("TpuTopology")[1]
        api.create("TpuTopology", CustomResource(
            "TpuTopology", "ring1", namespace="default",
            spec={"chips": 16}))
        # a built-in write must NOT leak into the CRD-scoped watch
        from kubernetes_tpu.api.types import make_node
        api.store.create("Node", make_node("noise", cpu=1, memory=1 << 20))
        evs = client.watch_since(("TpuTopology",), rv, timeout=1)
        assert [e.obj.name for e in evs] == ["ring1"]
        assert all(e.kind == "TpuTopology" for e in evs)
        with pytest.raises(NotFound):
            client.watch_since(("NoSuchKind",), rv, timeout=0.1)
    finally:
        srv.stop()
