"""End-to-end slice: apiserver-lite -> watch -> queue -> TPU batch engine ->
bind -> watch-confirm. The integration tier of SURVEY.md §7 step 4, mirroring
test/integration/scheduler/scheduler_test.go's shape (schedule+bind against a
real in-process apiserver) without kubelets."""

import dataclasses

from kubernetes_tpu.api.types import Binding, make_node, make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import density_pods, hollow_nodes, load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict
from tests.helpers import Gi, Mi


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_density_100_nodes_1k_pods_all_bound():
    api = ApiServerLite()
    nodes = hollow_nodes(100)
    pods = density_pods(1000)
    load_cluster(api, nodes, pods)
    sched = Scheduler(api)
    sched.start()
    totals = sched.run_until_drained()
    assert totals["bound"] == 1000
    assert totals["unschedulable"] == 0
    # every pod bound in the store; no node overcommitted
    bound, _ = api.list("Pod")
    per_node_cpu = {}
    per_node_count = {}
    for p in bound:
        assert p.node_name, f"{p.key()} not bound"
        per_node_cpu[p.node_name] = per_node_cpu.get(p.node_name, 0) + 100
        per_node_count[p.node_name] = per_node_count.get(p.node_name, 0) + 1
    for nm, cpu in per_node_cpu.items():
        assert cpu <= 4000
    for nm, cnt in per_node_count.items():
        assert cnt <= 110
    # watch-confirmation converted all assumed pods
    sched.sync()
    assert sched.cache.pod_count() == 1000
    assert not any(sched.cache.is_assumed(p.key()) for p in bound)
    assert sched.metrics.scheduled.value == 1000


def test_unschedulable_pod_backs_off_then_schedules_after_node_added():
    clock = FakeClock()
    api = ApiServerLite()
    api.create("Node", make_node("tiny", cpu=100, memory=128 * Mi))
    big = make_pod("big", cpu=4000, memory=8 * Gi)
    api.create("Pod", big)
    sched = Scheduler(api, now=clock)
    sched.start()
    stats = sched.schedule_round()
    assert stats["unschedulable"] == 1
    assert any(e.reason == "FailedScheduling" for e in sched.events)
    # still backing off: nothing ready
    stats = sched.schedule_round()
    assert stats["popped"] == 0
    # capacity arrives; after backoff expiry the pod schedules
    api.create("Node", make_node("beefy", cpu=8000, memory=32 * Gi))
    clock.t += 1.5  # initial backoff is 1s
    stats = sched.schedule_round()
    assert stats["bound"] == 1
    assert api.get("Pod", "default", "big").node_name == "beefy"


def test_bind_conflict_forgets_and_requeues():
    clock = FakeClock()
    api = ApiServerLite()
    api.create("Node", make_node("n0"))
    api.create("Node", make_node("n1"))
    pod = make_pod("contested", cpu=100, memory=128 * Mi)
    api.create("Pod", pod)
    sched = Scheduler(api, now=clock)
    sched.start()
    # an external scheduler binds the pod in the window between our queue pop
    # and our bind call (the race scheduler.go:234 handles via ForgetPod) —
    # injected by wrapping the batched bind path so the foreign bind lands
    # first
    real_bind_many = api.bind_many

    def racing_bind_many(bindings):
        api.bind_many = real_bind_many
        api.bind(Binding("contested", "default", pod.uid, "n1"))
        return real_bind_many(bindings)

    api.bind_many = racing_bind_many
    stats = sched.schedule_round()
    assert stats["bind_errors"] == 1
    assert any(e.reason == "FailedBinding" for e in sched.events)
    # our assume was rolled back; the confirmed foreign bind is in the cache
    sched.sync()
    assert sched.cache.pod_count() == 1
    infos = sched.cache.node_infos()
    assert len(infos["n1"].pods) == 1
    assert len(infos["n0"].pods) == 0
    # retry pops after backoff but bind target already set -> pod no longer
    # pending in store; the queue copy schedules then conflicts again, but
    # sync() removed it from the queue on MODIFIED -> nothing ready
    clock.t += 2.0
    stats = sched.schedule_round()
    assert stats["bound"] == 0


def test_pod_deletion_releases_capacity():
    api = ApiServerLite()
    api.create("Node", make_node("n0", cpu=1000, memory=2 * Gi))
    p1 = make_pod("a", cpu=800, memory=1 * Gi)
    api.create("Pod", p1)
    sched = Scheduler(api)
    sched.start()
    assert sched.schedule_round()["bound"] == 1
    sched.sync()
    # second pod can't fit until the first is deleted
    api.create("Pod", make_pod("b", cpu=800, memory=1 * Gi))
    assert sched.schedule_round()["unschedulable"] == 1
    api.delete("Pod", "default", "a")
    sched.sync()
    assert sched.cache.node_infos()["n0"].requested.milli_cpu == 0
    # give backoff time to expire (real clock: initial 1s)
    import time as _t
    _t.sleep(1.1)
    assert sched.schedule_round()["bound"] == 1


def test_node_deletion_reflected_in_cache():
    """Since ISSUE 8 a deleted node TOMBSTONES in place (node=None stub —
    the snapshot flips its row to valid=False instead of restructuring
    membership per churn event); it must never be placed on, and the
    amortized purge reclaims the entry."""
    api = ApiServerLite()
    api.create("Node", make_node("gone"))
    api.create("Node", make_node("stays"))
    sched = Scheduler(api)
    sched.start()
    api.delete("Node", "", "gone")
    sched.sync()
    infos = sched.cache.node_infos()
    assert set(infos.keys()) == {"gone", "stays"}
    assert infos["gone"].node is None  # tombstone, zero capacity
    api.create("Pod", make_pod("p", cpu=100))
    assert sched.schedule_round()["bound"] == 1
    assert api.get("Pod", "default", "p").node_name == "stays"
    assert sched.cache.purge_tombstones() == 1
    assert set(sched.cache.node_infos().keys()) == {"stays"}


def test_foreign_scheduler_pods_ignored():
    api = ApiServerLite()
    api.create("Node", make_node("n0"))
    mine = make_pod("mine", cpu=100)
    other = make_pod("other", cpu=100)
    other.scheduler_name = "custom-scheduler"
    api.create("Pod", mine)
    api.create("Pod", other)
    sched = Scheduler(api)
    sched.start()
    stats = sched.schedule_round()
    assert stats["bound"] == 1
    assert api.get("Pod", "default", "mine").node_name == "n0"
    assert api.get("Pod", "default", "other").node_name == ""


def test_density_100_nodes_3k_pods_meets_reference_floor():
    """TestSchedule100Node3KPods (scheduler_perf/scheduler_test.go:34-39,
    72-90): 100 nodes / 3,000 pods through the full control plane must
    sustain >= 30 pods/s (the reference's hard-fail floor; its warn level
    is 100 pods/s). The CPU test backend clears both by orders of
    magnitude — the assert pins the reference envelope, not our best."""
    import time as _time

    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, \
        load_cluster

    api = ApiServerLite(max_log=200_000)
    load_cluster(api, hollow_nodes(100), PROFILES["density"](3000))
    sched = Scheduler(api, record_events=False)
    sched.start()
    t0 = _time.monotonic()
    totals = sched.run_until_drained()
    elapsed = _time.monotonic() - t0
    assert totals["bound"] == 3000
    assert totals["unschedulable"] == 0
    pods_per_s = 3000 / elapsed
    assert pods_per_s >= 30, f"{pods_per_s:.1f} pods/s below the floor"
