"""Kubelet depth: probes, pressure eviction, pod workers, static pods.

Reference behaviors targeted (VERDICT r3 missing #2):
  pkg/kubelet/prober/prober_manager.go + worker.go   liveness/readiness
  pkg/kubelet/eviction/eviction_manager.go           pressure + QoS ranking
  pkg/kubelet/pod_workers.go                         per-pod serialization
  pkg/kubelet/config/file.go + mirror pods           static pod sources
plus the cross-component loops: readiness gates Endpoints membership
(endpoints_controller.go), pressure conditions feed the scheduler's
CheckNodeMemoryPressure/CheckNodeDiskPressure predicates.
"""

from __future__ import annotations

import dataclasses
import json

from kubernetes_tpu.api.types import (
    ConditionStatus,
    Probe,
    make_node,
    make_pod,
)
from kubernetes_tpu.api.workloads import Service, ServicePort
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.endpoint import EndpointController
from kubernetes_tpu.nodes.kubelet import (
    ACTUAL_MEM_ANNOTATION,
    LIVENESS_FAIL_AT_ANNOTATION,
    MIRROR_ANNOTATION,
    READY_AFTER_ANNOTATION,
    HollowFleet,
    PodWorkers,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from tests.test_nodes import FakeClock, mk_fleet

Mi = 1 << 20
Gi = 1 << 30


def _probe_pod(name, node, *, ready_after=None, liveness_fail_at=None,
               restart_policy="Always", cpu=100, labels=None):
    pod = make_pod(name, cpu=cpu, node_name=node, labels=labels or {})
    c = pod.containers[0]
    if ready_after is not None:
        c.readiness_probe = Probe(kind="httpGet", period_s=1.0,
                                  failure_threshold=1)
        pod.annotations[READY_AFTER_ANNOTATION] = str(ready_after)
    if liveness_fail_at is not None:
        c.liveness_probe = Probe(kind="httpGet", period_s=1.0,
                                 failure_threshold=3)
        pod.annotations[LIVENESS_FAIL_AT_ANNOTATION] = str(liveness_fail_at)
    pod.restart_policy = restart_policy
    return pod


# ------------------------------------------------------------------- probes


def test_readiness_probe_gates_ready_then_flips():
    api, factory, fleet, clock = mk_fleet()
    api.create("Pod", _probe_pod("web", "n0", ready_after=5.0))
    factory.step_all()
    fleet.step()
    p = api.get("Pod", "default", "web")
    assert p.phase == "Running" and p.ready is False  # probe not passed yet
    clock.t += 6.0
    fleet.step()
    assert api.get("Pod", "default", "web").ready is True


def test_liveness_failure_restarts_container():
    api, factory, fleet, clock = mk_fleet()
    api.create("Pod", _probe_pod("flaky", "n0", liveness_fail_at=10.0))
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "flaky").restart_count == 0
    clock.t += 11.0
    # failure_threshold=3 consecutive failed probes (one per period_s=1.0;
    # extra steps within a period do NOT re-probe) before restart
    fleet.step(); fleet.step()
    assert api.get("Pod", "default", "flaky").restart_count == 0
    clock.t += 1.0
    fleet.step()
    assert api.get("Pod", "default", "flaky").restart_count == 0
    clock.t += 1.0
    fleet.step()
    p = api.get("Pod", "default", "flaky")
    assert p.restart_count == 1
    assert p.ready is False  # unready during restart
    assert p.phase == "Running"  # restartPolicy Always: still running
    fleet.step()  # container back up (startup_latency 0)
    # it will fail again at +10s relative to the restart; before that it's
    # running with the restart recorded
    assert api.get("Pod", "default", "flaky").restart_count >= 1


def test_liveness_failure_with_restart_policy_never_fails_pod():
    api, factory, fleet, clock = mk_fleet()
    api.create("Pod", _probe_pod("oneshot", "n0", liveness_fail_at=1.0,
                                 restart_policy="Never"))
    factory.step_all()
    fleet.step()
    clock.t += 2.0
    for _ in range(3):  # three probe periods of failures
        fleet.step()
        clock.t += 1.0
    fleet.step()
    p = api.get("Pod", "default", "oneshot")
    assert p.phase == "Failed"
    assert p.annotations["kubernetes.io/failure-reason"] == "Unhealthy"


def test_readiness_gates_endpoints_membership():
    """The full loop: probe -> pod Ready condition -> endpoints controller
    includes/excludes the address (endpoints_controller.go)."""
    api, factory, fleet, clock = mk_fleet()
    api.create("Service", Service("svc", "default",
                                  selector={"app": "web"},
                                  ports=[ServicePort(port=80)]))
    api.create("Pod", _probe_pod("w0", "n0", ready_after=5.0,
                                 labels={"app": "web"}))
    api.create("Pod", make_pod("w1", cpu=100, node_name="n1",
                               labels={"app": "web"}))  # no probe: ready
    epc = EndpointController(api, factory, record_events=False)
    factory.step_all()
    fleet.step()
    factory.step_all()
    epc.pump()
    eps = api.get("Endpoints", "default", "svc")
    assert [a.pod_key for a in eps.addresses] == ["default/w1"]
    clock.t += 6.0
    fleet.step()  # probe passes -> w0 ready
    factory.step_all()
    epc.pump()
    eps = api.get("Endpoints", "default", "svc")
    assert [a.pod_key for a in eps.addresses] == ["default/w0", "default/w1"]


# ----------------------------------------------------------------- eviction


def test_memory_pressure_sets_condition_and_evicts_besteffort_first():
    api, factory, fleet, clock = mk_fleet(n_nodes=1)  # 1Gi allocatable
    # guaranteed-ish pod: requests==limits, modest usage
    g = make_pod("guaranteed", cpu=100, memory=256 * Mi, node_name="n0")
    g.containers[0].limits = dict(g.containers[0].requests)
    g.annotations[ACTUAL_MEM_ANNOTATION] = str(256 * Mi)
    # best-effort pod ballooning way past any request
    be = make_pod("balloon", node_name="n0")
    be.annotations[ACTUAL_MEM_ANNOTATION] = str(800 * Mi)
    api.create("Pod", g)
    api.create("Pod", be)
    factory.step_all()
    # one step: pods start AND the eviction pass sees usage 1056Mi > 95%
    # of the 1Gi allocatable
    fleet.step()
    balloon = api.get("Pod", "default", "balloon")
    assert balloon.phase == "Failed"
    assert balloon.annotations["kubernetes.io/failure-reason"] == "Evicted"
    assert api.get("Pod", "default", "guaranteed").phase == "Running"
    # pressure condition reaches the Node on the next heartbeat
    fleet.heartbeat_all()
    node = api.get("Node", "", "n0")
    assert node.condition("MemoryPressure") == ConditionStatus.TRUE
    # and clears once usage is back under the threshold
    fleet.step()
    fleet.heartbeat_all()
    assert api.get("Node", "", "n0").condition("MemoryPressure") \
        == ConditionStatus.FALSE


def test_scheduler_refuses_besteffort_on_memory_pressure_node():
    """Pressure condition -> CheckNodeMemoryPressure scheduler-side."""
    from kubernetes_tpu.engine.scheduler import Scheduler

    api, factory, fleet, clock = mk_fleet(n_nodes=2)
    be = make_pod("hog", node_name="n0")
    be.annotations[ACTUAL_MEM_ANNOTATION] = str(2 * Gi)
    api.create("Pod", be)
    factory.step_all()
    fleet.step()
    fleet.step()
    fleet.heartbeat_all()
    sched = Scheduler(api, record_events=False)
    sched.start()
    api.create("Pod", make_pod("new-be"))  # best-effort pending pod
    sched.run_until_drained()
    placed = api.get("Pod", "default", "new-be")
    assert placed.node_name == "n1", \
        "best-effort pod must avoid the MemoryPressure node"


# -------------------------------------------------------------- pod workers


def test_pod_workers_coalesce_updates():
    seen = []
    w = PodWorkers(lambda pod, op: seen.append((pod.name, op)))
    p = make_pod("x", node_name="n0")
    for _ in range(5):
        w.update_pod(p, "sync")
    w.update_pod(p, "remove")
    w.drain()
    assert seen == [("x", "remove")]  # latest wins, one sync
    assert w.coalesced == 5


def test_pod_workers_serialize_per_pod():
    order = []
    w = PodWorkers(lambda pod, op: order.append(pod.name))
    a, b = make_pod("a", node_name="n0"), make_pod("b", node_name="n0")
    w.update_pod(a, "sync")
    w.update_pod(b, "sync")
    assert w.drain() == 2
    assert sorted(order) == ["a", "b"]


# -------------------------------------------------------------- static pods


def test_static_pod_creates_mirror_and_survives_mirror_delete(tmp_path):
    api, factory, fleet, clock = mk_fleet()
    kl = fleet.kubelets["n0"]
    manifest = {
        "metadata": {"name": "static-web", "namespace": "default"},
        "spec": {"containers": [{"name": "c0", "resources":
                                 {"requests": {"cpu": "100m"}}}]},
    }
    (tmp_path / "pod.json").write_text(json.dumps(manifest))
    assert kl.load_static_dir(str(tmp_path)) == 1
    fleet.step()
    mirror = api.get("Pod", "default", "static-web")
    assert mirror.node_name == "n0"
    assert mirror.annotations.get(MIRROR_ANNOTATION) == "true"
    assert api.get("Pod", "default", "static-web").phase in ("Pending",
                                                             "Running")
    # deleting the mirror does not stop the static pod: it comes back
    api.delete("Pod", "default", "static-web")
    fleet.step()
    assert api.get("Pod", "default", "static-web").node_name == "n0"


# ------------------------------------------------------------------- scale


def test_fleet_probes_and_eviction_at_scale():
    """5k-node hollow fleet with probes + eviction active end-to-end:
    nodelifecycle-grade heartbeats carry pressure conditions, endpoints
    track readiness, one overloaded node evicts (the VERDICT's 'hollow
    fleet runs probes/eviction at 5k-node scale' done-condition)."""
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
    )

    clock = FakeClock()
    api = ApiServerLite(max_log=800_000)
    factory = SharedInformerFactory(api)
    fleet = HollowFleet(api, factory, now=clock)
    n_nodes = 5000
    for i in range(n_nodes):
        fleet.add_node(make_node(f"node-{i:04d}", cpu=4000, memory=1 * Gi,
                                 pods=110), register=True)
    api.create("Service", Service("svc", "default",
                                  selector={"app": "web"},
                                  ports=[ServicePort(port=80)]))
    # 200 probed service pods across the fleet + one ballooning best-effort
    for i in range(200):
        api.create("Pod", _probe_pod(f"w{i:03d}", f"node-{i:04d}",
                                     ready_after=5.0, labels={"app": "web"}))
    hog = make_pod("hog", node_name="node-0000")
    hog.annotations[ACTUAL_MEM_ANNOTATION] = str(2 * Gi)
    api.create("Pod", hog)
    epc = EndpointController(api, factory, record_events=False)
    nlc = NodeLifecycleController(api, factory, now=clock,
                                  record_events=False)
    factory.step_all()
    fleet.step()   # starts all pods; probes not yet passed
    fleet.step()   # eviction pass
    factory.step_all()
    epc.pump()
    nlc.pump()
    assert api.get("Pod", "default", "hog").phase == "Failed"
    eps = api.get("Endpoints", "default", "svc")
    assert eps.addresses == []  # nothing ready yet
    clock.t += 6.0
    fleet.step()
    factory.step_all()
    epc.pump()
    eps = api.get("Endpoints", "default", "svc")
    assert len(eps.addresses) == 200  # all probes passed
    fleet.heartbeat_all()
    factory.step_all()
    nlc.pump()
    # every node heartbeated: none evicted/tainted by nodelifecycle, and
    # the hog's node reported (then cleared) its pressure condition
    ready = [n for n in api.list("Node")[0]
             if n.condition("Ready") == ConditionStatus.TRUE]
    assert len(ready) == n_nodes


def test_scheduler_spreads_with_real_apiserver_service():
    """Regression: a Service stored as an apiserver object (api/workloads
    Service, not the scheduler-internal WorkloadObject) must flow through
    the spread path via to_workload_object — found by driving the full
    stack, previously crashed with AttributeError: no .selects."""
    from kubernetes_tpu.engine.scheduler import Scheduler

    api = ApiServerLite()
    for i in range(4):
        api.create("Node", make_node(f"n{i}", cpu=4000, memory=8 * Gi))
    api.create("Service", Service("svc", "default", selector={"app": "w"},
                                  ports=[ServicePort(port=80)]))
    for i in range(8):
        api.create("Pod", make_pod(f"w{i}", cpu=100, labels={"app": "w"}))
    sched = Scheduler(api, record_events=False)
    sched.start()
    totals = sched.run_until_drained()
    assert totals["bound"] == 8
    used = {p.node_name for p in api.list("Pod")[0]}
    assert len(used) == 4, "SelectorSpread must fan service pods out"


def test_disk_pressure_evicts_by_disk_usage_not_memory_request():
    """Regression (review): disk eviction must rank by disk usage over the
    DISK request — a pod with a big memory request but small disk use must
    not shield the actual disk hog."""
    from kubernetes_tpu.nodes.kubelet import ACTUAL_DISK_ANNOTATION, EvictionManager

    node = make_node("n0", cpu=4000, memory=8 * Gi)
    node.allocatable.storage_scratch = 10 * Gi
    em = EvictionManager(node)
    # burstable A: huge memory request, tiny disk use
    a = make_pod("a", cpu=100, memory=4 * Gi, node_name="n0")
    a.annotations[ACTUAL_DISK_ANNOTATION] = str(1 * Gi)
    # burstable B: small memory request, the actual disk hog
    b = make_pod("b", cpu=100, memory=64 * Mi, node_name="n0")
    b.annotations[ACTUAL_DISK_ANNOTATION] = str(9 * Gi)
    evict = em.synchronize({"default/a": a, "default/b": b})
    assert em.disk_pressure
    assert evict[0] == "default/b", f"disk hog must rank first, got {evict}"
