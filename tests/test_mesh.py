"""Device-mesh sharding tests (parallel/mesh.py).

The multi-chip story: node-indexed arrays sharded over a 1-D "nodes" mesh,
pod arrays replicated, XLA inserting the collectives (SURVEY.md §5.7 — the
tensor analog of workqueue.Parallelize(16, nodes) at
generic_scheduler.go:204,352). These tests run both engines under an
8-virtual-CPU-device mesh (tests/conftest.py) and assert bit-identical
placements vs the unsharded single-device run — sharding must be a pure
layout choice, never a semantics change.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubernetes_tpu.engine import waves
from kubernetes_tpu.engine.batch import node_state, place_batch
from kubernetes_tpu.ops import predicates as preds
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.parallel.mesh import (
    NODE_AXIS,
    make_mesh,
    replicate,
    shard_nodes,
)
from kubernetes_tpu.state.classes import ClassBatch
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch
from tests.helpers import Gi, Mi, random_nodes, random_pod

N_DEV = 8

PRIO = (("LeastRequestedPriority", 1), ("BalancedResourceAllocation", 1),
        ("TaintTolerationPriority", 1))


def _cluster(seed, n_nodes=24, n_pods=48):
    rng = random.Random(seed)
    nodes = random_nodes(rng, n_nodes)
    names = [n.name for n in nodes]
    pods = [random_pod(rng, i, names) for i in range(n_pods)]
    infos = node_info_map(nodes, [])
    # node axis padded to a multiple of the mesh size so shards are even
    snap = ClusterSnapshot(node_pad=N_DEV)
    snap.refresh(infos)
    return snap, pods


def test_make_mesh_and_shard_layout():
    mesh = make_mesh(N_DEV)
    assert mesh.devices.shape == (N_DEV,)
    snap, _ = _cluster(0)
    nodes = preds.node_arrays(snap)
    sharded = shard_nodes(nodes, mesh)
    n = int(nodes["alloc"].shape[0])
    assert n % N_DEV == 0
    # node-sharded arrays: each device holds exactly N/8 rows
    shards = sharded["alloc"].addressable_shards
    assert len(shards) == N_DEV
    assert all(s.data.shape[0] == n // N_DEV for s in shards)
    # replicated arrays: every device holds the full array
    rep = replicate({"x": jnp.arange(16)}, mesh)["x"]
    assert all(s.data.shape[0] == 16 for s in rep.addressable_shards)


@pytest.mark.parametrize("seed", [0, 2])
def test_fits_kernel_parity_under_mesh(seed):
    """static predicate matrix must be bit-identical sharded vs not."""
    snap, pods = _cluster(seed)
    batch = PodBatch(pods, snap)
    parr = preds.pod_arrays(batch)
    narr = preds.node_arrays(snap)
    base = np.asarray(preds.fits(parr, narr))

    mesh = make_mesh(N_DEV)
    with mesh:
        got = preds.fits(replicate(parr, mesh), shard_nodes(narr, mesh))
        got.block_until_ready()
    np.testing.assert_array_equal(np.asarray(got), base)
    # output inherits the node sharding on its node axis (axis 1)
    assert len({s.device for s in got.addressable_shards}) == N_DEV


@pytest.mark.parametrize("seed", [0, 1, 4])
def test_strict_engine_parity_under_mesh(seed):
    """place_batch (the bit-exact sequential scan) under an 8-device mesh
    must reproduce the single-device placement sequence exactly."""
    snap, pods = _cluster(seed)
    batch = PodBatch(pods, snap)
    parr = preds.pod_arrays(batch)
    narr = preds.node_arrays(snap)
    sel0, fc0, st0, rr0 = place_batch(parr, narr, node_state(narr),
                                      jnp.uint32(0), PRIO)
    base_sel, base_fc = np.asarray(sel0), np.asarray(fc0)

    mesh = make_mesh(N_DEV)
    with mesh:
        nsh = shard_nodes(narr, mesh)
        psh = replicate(parr, mesh)
        sel, fc, st, rr = place_batch(psh, nsh, node_state(nsh),
                                      jnp.uint32(0), PRIO)
        sel.block_until_ready()
    np.testing.assert_array_equal(np.asarray(sel), base_sel)
    np.testing.assert_array_equal(np.asarray(fc), base_fc)
    assert int(rr) == int(rr0)
    np.testing.assert_array_equal(np.asarray(st.requested),
                                  np.asarray(st0.requested))


@pytest.mark.parametrize("seed", [0, 3])
def test_wave_engine_parity_under_mesh(seed):
    """place_waves (throughput mode) sharded vs unsharded: same placements,
    same final capacity state."""
    snap, pods = _cluster(seed, n_pods=64)
    # wave path consumes class-level arrays
    cbatch = ClassBatch(pods, snap)
    cls = preds.pod_arrays(cbatch.reps_batch)
    narr = preds.node_arrays(snap)
    pc = cbatch.pod_class
    sel0, fc0, st0, rr0 = waves.place_waves(cls, narr, node_state(narr),
                                            pc, 0, PRIO)

    mesh = make_mesh(N_DEV)
    with mesh:
        nsh = shard_nodes(narr, mesh)
        csh = replicate(cls, mesh)
        sel, fc, st, rr = waves.place_waves(csh, nsh, node_state(nsh),
                                            pc, 0, PRIO)
    np.testing.assert_array_equal(sel, sel0)
    np.testing.assert_array_equal(fc, fc0)
    assert rr == rr0
    np.testing.assert_array_equal(np.asarray(st.pod_count),
                                  np.asarray(st0.pod_count))


def test_dryrun_multichip_impl_runs_in_process():
    """The driver-facing dryrun body itself (CPU backend is already forced
    by conftest, so the impl can run in-process here). Small explicit shape
    — the driver run uses the large default (2k nodes / 10k pods), which is
    minutes of CPU scan and belongs there, not in the suite."""
    import __graft_entry__ as g
    g._dryrun_multichip_impl(N_DEV, n_nodes=512, n_pending=288)


# ---------------------------------------------------------------- affinity


def _affinity_cluster(seed, n_nodes=24, n_existing=12, n_pending=32):
    """Cluster where the affinity machinery is genuinely exercised: existing
    guard pods with required anti-affinity, pending pods mixing required/
    preferred (anti-)affinity, and service workloads for spreading (reuses
    the fuzz generators of tests/test_affinity_fuzz.py)."""
    from tests.test_affinity_fuzz import _build_cluster, _pending
    rng = random.Random(seed)
    nodes, existing, workloads = _build_cluster(rng, n_nodes=n_nodes,
                                                n_existing=n_existing)
    pending = _pending(rng, n_pending)
    return nodes, existing, workloads, pending


def _affinity_kernel_inputs(nodes, existing, workloads, pending):
    """The exact array-construction path of SchedulingEngine.schedule."""
    from kubernetes_tpu.ops.affinity import (
        AffinityData,
        collect_pod_pairs,
        intern_topology_pairs,
    )
    from kubernetes_tpu.ops.predicates import bucket, pod_arrays_padded

    infos = node_info_map(nodes, existing)
    snap = ClusterSnapshot(node_pad=N_DEV)
    snap.refresh(infos)
    all_pairs, aff_pairs = collect_pod_pairs(infos)
    intern_topology_pairs(snap, pending, aff_pairs)
    cbatch = ClassBatch(pending, snap)
    c_pad = bucket(cbatch.num_classes + 1)
    adata = AffinityData(cbatch.reps, snap, all_pairs, aff_pairs,
                         workloads, 1, c_pad=c_pad)
    cls_arr = pod_arrays_padded(cbatch.reps_batch, c_pad)
    pc = np.full(preds.bucket(len(pending)), cbatch.num_classes,
                 dtype=np.int32)
    pc[: len(pending)] = cbatch.pod_class
    narr = preds.node_arrays(snap)
    return cls_arr, pc, narr, adata


@pytest.mark.parametrize("seed", [0, 5])
def test_strict_engine_affinity_parity_under_mesh(seed):
    """The flagship kernel — the full strict scan WITH the inter-pod
    affinity + spread machinery on — must be bit-identical sharded vs
    unsharded (VERDICT r3 #2: the [C,S,L]x[N,L] einsums' node axis is
    exactly what the mesh splits)."""
    from kubernetes_tpu.engine.batch import gather_place_batch
    from kubernetes_tpu.parallel.mesh import shard_affinity

    nodes, existing, workloads, pending = _affinity_cluster(seed)
    cls_arr, pc, narr, adata = _affinity_kernel_inputs(
        nodes, existing, workloads, pending)
    assert adata.fits_needed, "generator must exercise required affinity"
    assert adata.spread_needed or adata.prio_needed
    aff = adata.device_arrays()
    mode = (adata.fits_needed, adata.prio_needed, adata.spread_needed)
    sel0, fc0, st0, rr0 = gather_place_batch(
        cls_arr, jnp.asarray(pc), narr, node_state(narr),
        jnp.uint32(0), prio.DEFAULT_PRIORITIES, aff=aff, aff_mode=mode)
    base_sel, base_fc = np.asarray(sel0), np.asarray(fc0)
    assert (base_sel[: len(pending)] >= 0).any()

    mesh = make_mesh(N_DEV)
    with mesh:
        nsh = shard_nodes(narr, mesh)
        csh = replicate(cls_arr, mesh)
        ash = shard_affinity(aff, mesh)
        sel, fc, st, rr = gather_place_batch(
            csh, replicate({"pc": jnp.asarray(pc)}, mesh)["pc"], nsh,
            node_state(nsh), jnp.uint32(0), prio.DEFAULT_PRIORITIES,
            aff=ash, aff_mode=mode)
        sel.block_until_ready()
    np.testing.assert_array_equal(np.asarray(sel), base_sel)
    np.testing.assert_array_equal(np.asarray(fc), base_fc)
    assert int(rr) == int(rr0)
    np.testing.assert_array_equal(np.asarray(st.requested),
                                  np.asarray(st0.requested))
    np.testing.assert_array_equal(np.asarray(st.pod_count),
                                  np.asarray(st0.pod_count))


@pytest.mark.parametrize("seed", [1])
def test_frozen_affinity_scores_parity_under_mesh(seed):
    """Wave mode's batch-frozen spread/interpod score matrix [C,N] must be
    bit-identical sharded vs unsharded."""
    from kubernetes_tpu.engine.batch import node_state as mk_state
    from kubernetes_tpu.parallel.mesh import shard_affinity

    nodes, existing, workloads, pending = _affinity_cluster(seed)
    cls_arr, pc, narr, adata = _affinity_kernel_inputs(
        nodes, existing, workloads, pending)
    aff = adata.device_arrays()
    base = np.asarray(waves.frozen_affinity_scores(
        cls_arr, narr, mk_state(narr), aff, (2, 1)))
    mesh = make_mesh(N_DEV)
    with mesh:
        got = waves.frozen_affinity_scores(
            replicate(cls_arr, mesh), shard_nodes(narr, mesh),
            mk_state(shard_nodes(narr, mesh)), shard_affinity(aff, mesh),
            (2, 1))
        got.block_until_ready()
    np.testing.assert_array_equal(np.asarray(got), base)


# ------------------------------------------------- ISSUE 12: residency


def test_two_stage_tie_select_matches_global():
    """The winner-reduce contract: _ShardCol's two-stage tie selection
    (local rank + all-gathered [D, C] prefix + ownership-masked psum)
    must equal _GlobalCol's whole-axis tiemat lookup for every (class,
    draw) — including empty tie sets and ties straddling shard
    boundaries."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as PS

    from kubernetes_tpu.engine.waves import _GlobalCol, _ShardCol
    from kubernetes_tpu.parallel.mesh import NODE_AXIS

    rng = np.random.default_rng(7)
    C, N, P_ = 5, 64, 40
    ties = rng.random((C, N)) < 0.2
    ties[3] = False                      # empty tie set
    ties[4, N - 1] = True                # tie on the last shard edge
    ties_j = jnp.asarray(ties)
    pod_class = jnp.asarray(rng.integers(0, C, P_).astype(np.int32))
    m = ties.sum(axis=1).astype(np.int32)
    draw = rng.integers(0, 1000, P_).astype(np.int32)
    kz = jnp.asarray(draw % np.maximum(m[np.asarray(pod_class)], 1))

    base = _GlobalCol(N).tie_select(ties_j, pod_class, kz)

    mesh = make_mesh(N_DEV)
    col = _ShardCol(NODE_AXIS, N, N // N_DEV)
    got = shard_map(
        lambda t, pc, k: col.tie_select(t, pc, k),
        mesh=mesh, in_specs=(PS(None, NODE_AXIS), PS(), PS()),
        out_specs=PS(), check_rep=False)(ties_j, pod_class, kz)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))


def test_spmd_waves_loop_matches_global():
    """waves_loop(spmd_mesh=...) — the whole wave program under shard_map
    with the two-stage reduce — must produce the identical packed result
    and final NodeState as the single-program run (the tier-1 pin of the
    scale_sweep's bit-identity acceptance)."""
    snap, pods = _cluster(3, n_nodes=24, n_pods=48)
    cbatch = ClassBatch(pods, snap)
    cls = preds.pod_arrays(cbatch.reps_batch)
    narr = preds.node_arrays(snap)
    pc = jnp.asarray(cbatch.pod_class)
    ctr = jnp.uint32(0)
    packed0, st0 = waves.waves_loop(cls, narr, node_state(narr), pc, ctr,
                                    PRIO, 32)
    mesh = make_mesh(N_DEV)
    packed1, st1 = waves.waves_loop(cls, narr, node_state(narr), pc, ctr,
                                    PRIO, 32, spmd_mesh=mesh)
    np.testing.assert_array_equal(np.asarray(packed1), np.asarray(packed0))
    np.testing.assert_array_equal(np.asarray(st1.requested),
                                  np.asarray(st0.requested))
    np.testing.assert_array_equal(np.asarray(st1.pod_count),
                                  np.asarray(st0.pod_count))


def _mesh_sched(n_nodes, mesh):
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    load_cluster(api, hollow_nodes(n_nodes), [])
    s = Scheduler(api, record_events=False, mesh=mesh)
    s.start()
    return api, s


def test_resident_engine_partition_specs_and_identity():
    """Tier-1 mesh smoke (ISSUE 12): a tiny drain on the resident-mesh
    engine pins (a) the partition layout — node-axis device buffers
    sharded over all 8 devices, pod-side/class-side replicated — and
    (b) placements bit-identical to the unsharded engine."""
    from kubernetes_tpu.models.hollow import PROFILES

    def run(mesh):
        api, s = _mesh_sched(64, mesh)
        for p in PROFILES["density"](200):
            api.create("Pod", p)
        s.run_until_drained(max_batch=64)
        return api, s

    api0, _ = run(None)
    mesh = make_mesh(N_DEV)
    api1, s1 = run(mesh)
    p0 = {p.name: p.node_name for p in api0.list("Pod")[0]}
    p1 = {p.name: p.node_name for p in api1.list("Pod")[0]}
    assert p0 == p1 and all(p0.values())
    dev = s1.engine._device_nodes
    # node-axis arrays: one shard per device, rows split evenly
    for k in ("alloc", "requested", "labels", "pod_count"):
        shards = dev[k].addressable_shards
        assert len(shards) == N_DEV, k
        n = dev[k].shape[0]
        assert all(s.data.shape[0] == n // N_DEV for s in shards), k
    # pod-side tables stay replicated (pd_kind has no node axis)
    assert all(s.data.shape == dev["pd_kind"].shape
               for s in dev["pd_kind"].addressable_shards)
    # the sharded sync armed row tracking on the snapshot
    assert s1.engine.snapshot.dirty_rows is not None


def test_stream_sharded_equals_unsharded_frozen_trace():
    """ISSUE 12 satellite: the sharded==unsharded bit-identity A/B
    extended from the drain shapes to the STREAMING micro-wave path — the
    same frozen arrival trace consumed by two streaming loops (one
    mesh-resident, one unsharded) binds every pod to the same node, and
    the mesh run keeps the delta-only invariants: zero encode rebuilds
    after warmup and dynamic-row deltas riding the per-shard row path."""
    from kubernetes_tpu.models.hollow import PROFILES
    from kubernetes_tpu.utils.trace import COUNTERS

    trace = (37, 96, 5, 64)
    quantum = 128

    def run(mesh):
        api, s = _mesh_sched(48, mesh)
        loop = s.stream(budget_s=30.0, min_quantum=quantum,
                        max_quantum=quantum)
        # warm: one group compiles shapes + builds the encoding
        for p in PROFILES["density"](quantum):
            p.name = "warm-" + p.name
            api.create("Pod", p)
        loop.step()
        loop.drain()
        snap0 = COUNTERS.snapshot()
        for gi, group in enumerate(trace):
            pods = PROFILES["density"](group)
            for p in pods:
                p.name = f"g{gi}-{p.name}"
                api.create("Pod", p)
            loop.step()
        loop.drain()
        loop.close()
        snap1 = COUNTERS.snapshot()

        def delta(name):
            return snap1.get(name, (0, 0))[0] - snap0.get(name, (0, 0))[0]
        return ({p.name: p.node_name for p in api.list("Pod")[0]},
                {k: delta(k) for k in ("engine.wave_encode_build",
                                       "engine.shard_delta_rows",
                                       "snapshot.assume_delta_rows")})

    pa, _ = run(None)
    pb, counters = run(make_mesh(N_DEV))
    assert pa == pb, {k: (pa[k], pb[k]) for k in pa if pa[k] != pb[k]}
    assert all(v for v in pa.values())
    # delta-only invariant, mesh edition: no re-tensorization mid-stream,
    # and the assume folds shipped through the per-shard row path
    assert counters["engine.wave_encode_build"] == 0
    assert counters["engine.shard_delta_rows"] > 0
    assert counters["snapshot.assume_delta_rows"] >= sum(trace)
