"""Host-check + Policy chunks ride the wave path (ISSUE 18).

Before this change, a chunk containing any host-check class (node
selector/zone/PV-affinity overflow, host ports) or any Policy-configured
algorithm forced the streaming pipeline to FLUSH and fall back to a
classic serialized round. Now nothing serializes on chunk shape:

  * label-pure host-check classes fold into the fused [C, N] eval as a
    precomputed `host_fit` column (exact AND of an exact predicate),
  * dynamic host-check classes (ports, score-affecting preference
    overflow, Policy needs_host) ride as inactive rows and place at the
    harvest's exact oracle tail,
  * Policy chunks ride with frozen policy_fit/policy_score columns plus
    a fence-side exact re-check against live truth.

These tests pin (a) the classification split, (b) the no-flush routing
guard via span counters, (c) bit-identity against the classic round on
a frozen trace with unique winners, and (d) the conservative stale-fence
requeue when a relabel lands while a host_static wave is in flight."""

from __future__ import annotations

import copy

from kubernetes_tpu.api.policy import parse_policy
from kubernetes_tpu.api.types import (
    Affinity,
    ContainerPort,
    NodeAffinity,
    NodeSelectorTerm,
    SelectorOperator,
    SelectorRequirement,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.models.hollow import load_cluster
from kubernetes_tpu.observability import podtrace
from kubernetes_tpu.ops.policy_algos import algorithms_from_policy
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.utils.trace import COUNTERS

Gi = 1 << 30


def zone_term(z):
    return NodeSelectorTerm([SelectorRequirement(
        "zone", SelectorOperator.IN, [z])])


def overflow_affinity(zone, n_bogus=4):
    """5 ORed required terms (> max_terms=4) -> the class overflows the
    batch encoding and becomes a host-check class; only `zone` exists on
    any node, so the pod has a unique feasible zone."""
    terms = [zone_term(zone)] + [zone_term(f"bogus-{i}")
                                 for i in range(n_bogus)]
    return Affinity(node_affinity=NodeAffinity(required_terms=terms))


def ports_pod(name, n_ports=10, **kw):
    """> MAX_PORTS_PER_POD host ports -> dynamic host-check (live pod
    state), rides as an inactive row to the exact oracle tail."""
    p = make_pod(name, cpu=100, memory=128 << 20, **kw)
    p.containers[0].ports = [ContainerPort(host_port=9000 + i)
                             for i in range(n_ports)]
    return p


def mk_sched(nodes, pods, chunk, policy=None):
    api = ApiServerLite()
    load_cluster(api, nodes, pods)
    s = Scheduler(api, record_events=False, policy=policy)
    s.pipeline_chunk = chunk
    s.start()
    return api, s


def placements(api):
    return {p.name: p.node_name for p in api.list("Pod")[0]}


# ------------------------------------------------------- classification


def test_host_static_vs_dynamic_classification():
    """The split that makes the ride possible: label-pure causes become
    host_static (exact precomputed column, stays active on the wave);
    live-state causes become host_exact (inactive row, oracle tail)."""
    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu=4000, memory=16 * Gi,
                                 pods=110, labels={"zone": f"z{i}"}))
    eng = SchedulingEngine(cache)
    static_pod = make_pod("hs", cpu=100, memory=128 << 20)
    static_pod.affinity = overflow_affinity("z1")
    plain = make_pod("plain", cpu=100, memory=128 << 20)
    pods = [static_pod, ports_pod("hx"), plain]
    handle = eng.dispatch_waves(pods)
    assert handle is not None, "host-check chunks must dispatch"
    enc, pc = handle.enc, handle.pc
    assert enc.host_static[pc[0]] and not enc.host_exact[pc[0]]
    assert enc.host_exact[pc[1]] and not enc.host_static[pc[1]]
    assert not enc.host_static[pc[2]] and not enc.host_exact[pc[2]]
    h = eng.harvest_waves(handle)
    by_name = {p.name: p.node_name for p in h.bound}
    assert by_name["hs"] == "n1", by_name  # exact host_fit column applied
    assert "hx" in by_name and "plain" in by_name
    assert not h.unschedulable and not h.conflicts


def test_host_exact_only_chunk_dispatches():
    """A chunk that is ENTIRELY dynamic host-check still dispatches (the
    wave retires immediately; the tail places everything) — no shape
    triggers the classic fallback."""
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu=4000, memory=16 * Gi, pods=110))
    cache.add_node(make_node("n1", cpu=4000, memory=16 * Gi, pods=110))
    eng = SchedulingEngine(cache)
    handle = eng.dispatch_waves([ports_pod("hx-0"), ports_pod("hx-1")])
    assert handle is not None
    h = eng.harvest_waves(handle)
    assert {p.name for p in h.bound} == {"hx-0", "hx-1"}
    # host-port exclusivity held by the FIFO oracle tail (assume between
    # pods): the two 10-port pods cannot share a node
    assert len({p.node_name for p in h.bound}) == 2


# ------------------------------------------------- the no-flush routing


NLP_POLICY = parse_policy("""{
  "predicates": [{"name": "CustomLabelsPresence", "argument":
    {"labelsPresence": {"labels": ["foo"], "presence": true}}}],
  "priorities": [{"name": "EqualPriority", "weight": 1}]}""")


def test_mixed_hostcheck_policy_drain_never_flushes():
    """The routing guard: a mixed drain of plain + host_static +
    host_exact + Policy-constrained chunks must complete with ZERO
    pipeline flushes (span counters prove it) while every constraint
    holds exactly."""
    nodes = [make_node(f"n{i}", cpu=8000, memory=32 * Gi, pods=110,
                       labels={"zone": f"z{i % 4}", "foo": "x"})
             for i in range(6)]
    nodes += [make_node(f"bare{i}", cpu=8000, memory=32 * Gi, pods=110)
              for i in range(2)]  # no foo -> Policy must exclude these
    pods = []
    for i in range(6):
        pods.append(make_pod(f"plain-{i}", cpu=100, memory=128 << 20))
    for i in range(4):
        p = make_pod(f"hs-{i}", cpu=100, memory=128 << 20)
        p.affinity = overflow_affinity(f"z{i % 4}")
        pods.append(p)
    pods.append(ports_pod("hx-0"))
    COUNTERS.reset()
    api, s = mk_sched(nodes, pods, chunk=4, policy=NLP_POLICY)
    tot = s.run_until_drained()
    snap = COUNTERS.snapshot()
    assert tot["bound"] == len(pods), tot
    assert snap.get("stream.chunk_flush", (0, 0))[0] == 0, \
        "host-check/Policy chunks must not flush the pipeline"
    assert snap["engine.wave_dispatch"][0] >= 2
    assert snap["engine.wave_host_rows"][0] >= 1   # the ports pod rode
    assert snap["engine.wave_host_tail"][0] >= 1   # ... and placed at tail
    got = placements(api)
    for nm, node in got.items():
        assert not node.startswith("bare"), \
            f"{nm} on {node}: Policy labelsPresence violated on the wave"
    for i in range(4):
        node = got[f"hs-{i}"]
        want_zone = f"z{i % 4}"
        node_obj = {n.name: n for n in nodes}[node]
        assert node_obj.labels.get("zone") == want_zone, \
            f"hs-{i} on {node}: host_static selector violated"


# ------------------------------------------------- frozen-trace A/B


def _unique_winner_trace():
    """Every pod has exactly one feasible/best node, so wave-kernel vs
    strict-oracle tie-breaking cannot diverge: the A/B pins SEMANTICS,
    not scheduling luck."""
    nodes = [make_node(f"n{i}", cpu=8000, memory=32 * Gi, pods=110,
                       labels={"zone": f"z{i}", "foo": "x"})
             for i in range(6)]
    pods = []
    for i in range(4):        # host_static, unique winner n{i}
        p = make_pod(f"hs-{i}", cpu=100, memory=128 << 20)
        p.affinity = overflow_affinity(f"z{i}")
        pods.append(p)
    # host_exact (ports) pinned to n4 by an equality selector
    pods.append(ports_pod("hx-0", node_selector={"zone": "z4"}))
    # plain pod pinned to n5 (equality selector is batch-expressible,
    # stays on the fast path — covers the mixed chunk)
    pods.append(make_pod("pin-5", cpu=100, memory=128 << 20,
                         node_selector={"zone": "z5"}))
    return nodes, pods


def test_wave_routed_hostcheck_matches_classic_bit_identical():
    """Frozen-trace A/B: the same trace through (a) the pipelined wave
    path, (b) the classic serialized rounds, and (c) the wave path with
    overlap forced off must produce bit-identical placements."""
    nodes, pods = _unique_winner_trace()
    api_a, s_a = mk_sched(copy.deepcopy(nodes), copy.deepcopy(pods),
                          chunk=3)
    s_a.run_until_drained()
    api_b, s_b = mk_sched(copy.deepcopy(nodes), copy.deepcopy(pods),
                          chunk=3)
    s_b.run_until_drained(pipeline=False)
    api_c, s_c = mk_sched(copy.deepcopy(nodes), copy.deepcopy(pods),
                          chunk=3)
    s_c.run_until_drained(overlap=False)
    got = placements(api_a)
    assert got == placements(api_b), "wave-routed != classic round"
    assert got == placements(api_c), "overlap on/off diverged"
    want = {"hs-0": "n0", "hs-1": "n1", "hs-2": "n2", "hs-3": "n3",
            "hx-0": "n4", "pin-5": "n5"}
    assert got == want, got


def test_policy_wave_matches_classic_bit_identical():
    """Same A/B for a Policy-constrained trace: labelsPresence admits a
    single node, so the frozen policy_fit column, the fence re-check,
    and the classic oracle must all land every pod identically."""
    nodes = [make_node("ok", cpu=8000, memory=32 * Gi, pods=110,
                       labels={"foo": "x"}),
             make_node("bare-a", cpu=8000, memory=32 * Gi, pods=110),
             make_node("bare-b", cpu=8000, memory=32 * Gi, pods=110)]
    pods = [make_pod(f"p{i}", cpu=100, memory=128 << 20)
            for i in range(5)]
    api_a, s_a = mk_sched(copy.deepcopy(nodes), copy.deepcopy(pods),
                          chunk=2, policy=NLP_POLICY)
    s_a.run_until_drained()
    api_b, s_b = mk_sched(copy.deepcopy(nodes), copy.deepcopy(pods),
                          chunk=2, policy=NLP_POLICY)
    s_b.run_until_drained(pipeline=False)
    got = placements(api_a)
    assert got == placements(api_b)
    assert all(v == "ok" for v in got.values()), got


# ------------------------------------------------- the stale fence


def test_relabel_in_flight_requeues_hostcheck_conservatively():
    """A relabel landing while a host_static wave is in flight makes the
    baked host_fit column stale: the fence must requeue the row with
    REASON_HOSTCHECK (conservative — relabels are rare), and the
    re-dispatch must rebuild against fresh label truth and place on the
    NEW matching node."""
    cache = SchedulerCache()
    n0 = make_node("n0", cpu=4000, memory=16 * Gi, pods=110,
                   labels={"zone": "z0"})
    n1 = make_node("n1", cpu=4000, memory=16 * Gi, pods=110,
                   labels={"zone": "zx"})
    cache.add_node(n0)
    cache.add_node(n1)
    eng = SchedulingEngine(cache)
    pod = make_pod("hs", cpu=100, memory=128 << 20)
    pod.affinity = overflow_affinity("z0")
    COUNTERS.reset()
    handle = eng.dispatch_waves([pod])
    assert handle is not None
    assert handle.enc.host_static[handle.pc[0]]
    # the blind window: z0 MOVES from n0 to n1 while the wave is in flight
    n0b = copy.deepcopy(n0)
    n0b.labels = {"zone": "zb"}
    n1b = copy.deepcopy(n1)
    n1b.labels = {"zone": "z0"}
    cache.update_node(n0b)
    cache.update_node(n1b)
    h = eng.harvest_waves(handle)
    assert not h.bound, "stale host_fit row must not bind"
    assert [p.name for p in h.conflicts] == ["hs"]
    assert h.conflict_reasons == [podtrace.REASON_HOSTCHECK]
    snap = COUNTERS.snapshot()
    assert snap["engine.hostcheck_fence_requeues"][0] == 1
    assert snap["engine.fence_reason_host_check"][0] == 1
    # conservative requeue -> re-dispatch rebuilds the column against the
    # refreshed labels and places on the node that NOW carries z0
    handle2 = eng.dispatch_waves([pod])
    h2 = eng.harvest_waves(handle2)
    assert [(p.name, p.node_name) for p in h2.bound] == [("hs", "n1")]


def test_fresh_labels_do_not_requeue_hostcheck():
    """Control for the stale fence: with no relabel in the blind window a
    host_static row binds first try — the conservative requeue must not
    fire spuriously (it would halve wave throughput for these classes)."""
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu=4000, memory=16 * Gi, pods=110,
                             labels={"zone": "z0"}))
    eng = SchedulingEngine(cache)
    pod = make_pod("hs", cpu=100, memory=128 << 20)
    pod.affinity = overflow_affinity("z0")
    COUNTERS.reset()
    h = eng.harvest_waves(eng.dispatch_waves([pod]))
    assert [(p.name, p.node_name) for p in h.bound] == [("hs", "n0")]
    snap = COUNTERS.snapshot()
    assert snap.get("engine.hostcheck_fence_requeues", (0, 0))[0] == 0
