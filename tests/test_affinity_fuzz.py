"""Randomized affinity-kernel-vs-oracle differential fuzz.

The style the reference uses at scale in predicates_test.go (3,661-line
table) / interpod_affinity_test.go, generated randomly instead: clusters
with existing affinity-bearing pods, workload selectors, and pending pods
mixing required/preferred (anti-)affinity. The engine's device path
(ops/affinity.py through engine/batch.py) must match, placement for
placement, the object-level oracle running the reference's sequential
scheduleOne loop (ops/oracle.py + ops/oracle_ext.py).

This is precisely the test class that would have caught the r2 symmetry
bug (VERDICT r2 weak #2: topology keys referenced only by EXISTING pods'
terms missing from the label vocab): existing pods here carry anti-affinity
over keys the pending batch never selects on.
"""

import copy
import random

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    WorkloadObject,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops.oracle_ext import SchedulingContext
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.node_info import node_info_map
from tests.helpers import Gi

APPS = ["web", "store", "db", "cache", "batch"]
TOPO_KEYS = ["zone", "rack", "room"]  # deliberately NOT selector-referenced


def _term(rng, key=None):
    app = rng.choice(APPS)
    return PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": app}),
        namespaces=[], topology_key=key or rng.choice(TOPO_KEYS))


def _random_affinity(rng):
    """Maybe-None Affinity with random required/preferred (anti-)terms."""
    aff = None
    anti = None
    if rng.random() < 0.5:
        req = [_term(rng) for _ in range(rng.randint(0, 2))]
        pref = [(rng.randint(1, 100), _term(rng))
                for _ in range(rng.randint(0, 2))]
        if req or pref:
            aff = PodAffinity(required_terms=req, preferred_terms=pref)
    if rng.random() < 0.5:
        req = [_term(rng) for _ in range(rng.randint(0, 1))]
        pref = [(rng.randint(1, 100), _term(rng))
                for _ in range(rng.randint(0, 2))]
        if req or pref:
            anti = PodAffinity(required_terms=req, preferred_terms=pref)
    if aff is None and anti is None:
        return None
    return Affinity(pod_affinity=aff, pod_anti_affinity=anti)


def _build_cluster(rng, n_nodes=8, n_existing=10):
    nodes = []
    for i in range(n_nodes):
        labels = {"host": f"h{i}"}
        for k in TOPO_KEYS:
            if rng.random() < 0.85:  # some nodes MISS topology keys
                labels[k] = f"{k}-{rng.randint(0, 2)}"
        nodes.append(make_node(f"node-{i}", cpu=8000, memory=32 * Gi,
                               pods=110, labels=labels))
    existing = []
    for i in range(n_existing):
        p = make_pod(f"bound-{i}", cpu=100,
                     labels={"app": rng.choice(APPS)})
        p.affinity = _random_affinity(rng)
        p.node_name = rng.choice(nodes).name
        existing.append(p)
    workloads = [
        WorkloadObject("Service", f"svc-{a}", "default",
                       match_labels={"app": a})
        for a in APPS if rng.random() < 0.6
    ]
    return nodes, existing, workloads


def _pending(rng, n):
    out = []
    for i in range(n):
        p = make_pod(f"pend-{i}", cpu=rng.choice([100, 500]),
                     labels={"app": rng.choice(APPS)})
        if rng.random() < 0.6:
            p.affinity = _random_affinity(rng)
        out.append(p)
    return out


def _oracle_sequence(nodes, existing, workloads, pending, priorities,
                     hard_weight=1):
    infos = node_info_map(nodes, existing)
    names = sorted(infos.keys())
    rr = oracle.RoundRobin()
    ctx = SchedulingContext(infos, workloads,
                            hard_pod_affinity_weight=hard_weight)
    out = []
    for pod in pending:
        name = oracle.schedule_one(pod, names, infos, rr, priorities, ctx)
        out.append(name)
        if name is not None:
            p = copy.deepcopy(pod)
            p.node_name = name
            infos[name].add_pod(p)
            ctx.invalidate()
    return out


def _engine_sequence(nodes, existing, workloads, pending, priorities,
                     mode="strict"):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(copy.deepcopy(p))
    eng = SchedulingEngine(cache, priorities=priorities,
                           workloads_provider=lambda: workloads)
    results = eng.schedule([copy.deepcopy(p) for p in pending], mode=mode)
    return [r.node_name for r in results]


from kubernetes_tpu.ops import priorities as prio

PSETS = [
    prio.DEFAULT_PRIORITIES,
    (("InterPodAffinityPriority", 2), ("LeastRequestedPriority", 1)),
    (("SelectorSpreadPriority", 1), ("EqualPriority", 1)),
]


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 7])
def test_affinity_strict_matches_oracle(seed):
    rng = random.Random(seed)
    nodes, existing, workloads = _build_cluster(rng)
    pending = _pending(rng, 14)
    pset = PSETS[seed % len(PSETS)]
    want = _oracle_sequence(nodes, existing, workloads, pending, pset)
    got = _engine_sequence(nodes, existing, workloads, pending, pset)
    assert got == want


def test_symmetry_only_cluster_matches_oracle():
    """Pure regression axis for the r2 vocab bug: ONLY existing pods carry
    (anti-)affinity; the pending batch is plain pods whose labels match the
    existing terms. Every topology key reaches the vocab solely via
    intern_topology_pairs."""
    rng = random.Random(42)
    nodes, _, workloads = _build_cluster(rng, n_existing=0)
    existing = []
    for i in range(6):
        p = make_pod(f"guard-{i}", cpu=100, labels={"app": "guard"})
        p.affinity = Affinity(pod_anti_affinity=PodAffinity(
            required_terms=[_term(rng)]))
        p.node_name = nodes[i % len(nodes)].name
        existing.append(p)
    pending = [make_pod(f"plain-{i}", cpu=100,
                        labels={"app": rng.choice(APPS)})
               for i in range(10)]
    pset = prio.DEFAULT_PRIORITIES
    want = _oracle_sequence(nodes, existing, workloads, pending, pset)
    got = _engine_sequence(nodes, existing, workloads, pending, pset)
    assert got == want


def _violates_required_anti(placements, nodes_by_name, all_pods):
    """Invariant checker: no placement may co-locate (same topology domain)
    with any pod whose required anti-affinity matches it, nor place a pod
    whose own required anti-affinity matches a resident (predicates.go:982,
    1146 — both directions of the symmetry)."""
    from kubernetes_tpu.ops.oracle_ext import (
        nodes_same_topology,
        term_matches_pod,
        _own_terms,
    )
    for pod, node_name in placements:
        if node_name is None:
            continue
        node = nodes_by_name[node_name]
        for other, other_node_name in all_pods:
            if other is pod or other_node_name is None:
                continue
            other_node = nodes_by_name[other_node_name]
            for t in _own_terms(other, anti=True):
                if term_matches_pod(t, other, pod) and \
                        nodes_same_topology(node, other_node, t.topology_key):
                    return f"{other.name} anti-term violated by {pod.name}"
            for t in _own_terms(pod, anti=True):
                if term_matches_pod(t, pod, other) and \
                        nodes_same_topology(node, other_node, t.topology_key):
                    return f"{pod.name} own anti-term violated at {node_name}"
    return None


def _violates_required_aff(placements, nodes_by_name, all_pods):
    """Allow-side oracle: a placed pod's required AFFINITY terms must each
    be satisfied by some other pod sharing the topology domain — except the
    legitimate bootstrap (the term self-matches and no other matching pod
    is bound anywhere, predicates.go:1210-1230). Catches the blind-window
    hazard of two chunks bootstrapping one group into different domains."""
    from kubernetes_tpu.ops.oracle_ext import (
        _own_terms,
        nodes_same_topology,
        term_matches_pod,
    )
    for pod, node_name in placements:
        if node_name is None:
            continue
        node = nodes_by_name[node_name]
        for t in _own_terms(pod, anti=False):
            matches = [(q, qn) for q, qn in all_pods
                       if q is not pod and qn is not None
                       and term_matches_pod(t, pod, q)]
            if not matches:
                if term_matches_pod(t, pod, pod):
                    continue  # lone bootstrap: nothing else to co-locate with
                return f"{pod.name}: term has no matching pod at all"
            if not any(nodes_same_topology(node, nodes_by_name[qn],
                                           t.topology_key)
                       for _q, qn in matches):
                return f"{pod.name}: required affinity unmet at {node_name}"
    return None


def _build_pipeline_cluster(rng, n_nodes=10, n_existing=6):
    """Like _build_cluster but with a HOSTNAME key in every node's labels so
    the fuzz exercises the wave path (singleton domains), not only the
    strict tail, and some existing anti-affinity guards for the static
    symmetry side."""
    nodes, existing, _w = _build_cluster(rng, n_nodes=n_nodes,
                                         n_existing=n_existing)
    return nodes, existing


def _pending_required_mix(rng, n):
    """Pending pods over required-only (anti-)affinity mixes: hostname anti
    (wave-expressible), zone/rack anti and zone affinity (strict tail),
    plain pods sharing labels with the anti apps (symmetry targets)."""
    out = []
    for i in range(n):
        app = rng.choice(APPS)
        p = make_pod(f"pp-{i}", cpu=rng.choice([100, 500]),
                     labels={"app": app})
        roll = rng.random()
        if roll < 0.25:
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[_term(rng, key="host")]))
        elif roll < 0.40:
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[_term(rng)]))  # zone/rack/room: multi-node
        elif roll < 0.50:
            p.affinity = Affinity(pod_affinity=PodAffinity(
                required_terms=[_term(rng)]))
        out.append(p)
    return out


def _drain_pipelined(nodes, existing, pending, overlap=True, chunk=4,
                     tail_rounds=None):
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    load_cluster(api, nodes, [])
    for p in existing:
        api.create("Pod", copy.deepcopy(p))
    for p in pending:
        api.create("Pod", copy.deepcopy(p))
    s = Scheduler(api, record_events=False)
    if tail_rounds is True:       # force the conflict-round tail even for
        s.engine.tail_rounds_min = 0   # tiny tails (the fuzz shapes)
    elif tail_rounds is False:    # per-pod scan oracle mode
        s.engine.tail_rounds = False
    s.pipeline_chunk = chunk
    # unschedulable-retry backoff promotes on WALL CLOCK — under load a
    # retry can join a different chunk in the overlapped run than in the
    # sequential one, which legally shifts RR draws and breaks the
    # bit-identity this A/B asserts. Zero the initial delay so a retry
    # always promotes at the very next pop, load-independent; retries
    # themselves (the behavior under test) still happen.
    s.queue.backoff._initial = 0.0
    s.start()
    s.run_until_drained(max_batch=chunk, overlap=overlap)
    return {p.name: (p.node_name or None) for p in api.list("Pod")[0]}


@pytest.mark.parametrize("seed", [0, 1, 5, 9])
def test_pipelined_affinity_wave_vs_strict_oracle(seed):
    """ISSUE 3 fuzz: the pipelined drain places required-(anti-)affinity
    chunks through the wave path (per-wave topology occupancy + seeded
    strict tail + fence). The STRICT SCAN'S constraint semantics are the
    oracle: no placement may violate required anti-affinity in either
    direction (own terms and the symmetry check, predicates.go:982/1146),
    and every required-affinity term must be co-location-satisfied (modulo
    the lone-bootstrap rule) — on the final cluster state, existing guard
    pods included. The overlap A/B must be bit-identical: the fence, not
    timing, decides every blind conflict."""
    rng = random.Random(seed)
    nodes, existing = _build_pipeline_cluster(rng)
    # give every node a "host" singleton key so hostname anti rides waves
    for i, n in enumerate(nodes):
        n.labels.setdefault("host", f"h{i}")
    pending = _pending_required_mix(rng, 18)
    got = _drain_pipelined(nodes, existing, pending)
    nodes_by_name = {n.name: n for n in nodes}
    all_pods = [(p, p.node_name) for p in existing] + \
        [(p, got.get(p.name)) for p in pending]
    placements = [(p, got.get(p.name)) for p in pending]
    err = _violates_required_anti(placements, nodes_by_name, all_pods)
    assert err is None, err
    err = _violates_required_aff(placements, nodes_by_name, all_pods)
    assert err is None, err
    # A/B: identical dataflow, overlap off -> bit-identical placements
    got2 = _drain_pipelined(nodes, existing, pending, overlap=False)
    assert got == got2


@pytest.mark.parametrize("seed", [2, 6])
def test_pipelined_affinity_chunks_do_not_flush(seed):
    """Routing guard: a drain whose chunks mix plain and required-affinity
    pods must stay wave-granular — every chunk dispatches as a wave (no
    classic-round fallback), inexpressible shapes go to the strict tail,
    and the tail is never silently skipped."""
    from kubernetes_tpu.utils.trace import COUNTERS

    rng = random.Random(seed)
    nodes, existing = _build_pipeline_cluster(rng)
    for i, n in enumerate(nodes):
        n.labels.setdefault("host", f"h{i}")
    pending = _pending_required_mix(rng, 16)
    n_strict_expected = 0  # multi-node-domain anti + zone affinity shapes
    for p in pending:
        a = p.affinity
        if a is None:
            continue
        terms = []
        if a.pod_affinity is not None:
            terms += [(t, True) for t in a.pod_affinity.required_terms]
        if a.pod_anti_affinity is not None:
            terms += [(t, False) for t in a.pod_anti_affinity.required_terms]
        if any(aff or t.topology_key != "host" for t, aff in terms):
            n_strict_expected += 1
    COUNTERS.reset()
    got = _drain_pipelined(nodes, existing, pending)
    snap = COUNTERS.snapshot()
    assert snap.get("engine.wave_dispatch", (0, 0))[0] >= 2, snap
    tail = snap.get("engine.affinity_strict_tail", (0, 0))[0]
    # requeues may send a strict pod through the tail more than once
    assert tail >= n_strict_expected, (tail, n_strict_expected, snap)


@pytest.mark.parametrize("seed", [0, 3, 11])
def test_wave_mode_required_affinity_invariants(seed):
    """Wave mode's preferred scoring is a documented batch-frozen
    approximation, so placements may diverge from strict — but REQUIRED
    (anti-)affinity must never be violated, and schedulability must agree
    for pods the strict engine places."""
    rng = random.Random(seed)
    nodes, existing, workloads = _build_cluster(rng)
    pending = _pending(rng, 12)
    got = _engine_sequence(nodes, existing, workloads, pending,
                           prio.DEFAULT_PRIORITIES, mode="wave")
    nodes_by_name = {n.name: n for n in nodes}
    all_pods = [(p, p.node_name) for p in existing] + \
        [(p, nm) for p, nm in zip(pending, got)]
    placements = [(p, nm) for p, nm in zip(pending, got)]
    err = _violates_required_anti(placements, nodes_by_name, all_pods)
    assert err is None, err


@pytest.mark.parametrize("seed", [1, 4, 8])
def test_tail_rounds_vs_scan_tail_oracle(seed):
    """ISSUE 5 fuzz: the conflict-round tail (waves.tail_rounds_loop,
    forced on via tail_rounds_min=0) against the per-pod scan tail
    (GRAFT_TAIL_ROUNDS=0 semantics) on the same required-affinity mixes.
    The rounds tail re-evaluates the REQUIRED mask exactly every round,
    so both modes must satisfy the strict constraint oracle — anti in
    both directions (own terms + the symmetry check) and allow-side
    co-location with the lone-bootstrap rule — and must agree on the
    requeue/schedulability outcome (same pods bound: monotone capacity
    plus exact masks make the verdicts mode-independent on these
    shapes). Tie-breaks may diverge (wave-style fan-out vs the classic
    serialized order — the documented wave-path divergence), so node
    assignments are NOT compared. Each mode must also be deterministic:
    the overlap=False A/B is bit-identical per mode, which pins the
    requeue ORDER (a reordered requeue changes RR draws and with them
    the placements)."""
    rng = random.Random(seed)
    nodes, existing = _build_pipeline_cluster(rng)
    for i, n in enumerate(nodes):
        n.labels.setdefault("host", f"h{i}")
    pending = _pending_required_mix(rng, 18)
    nodes_by_name = {n.name: n for n in nodes}
    results = {}
    for mode in (True, False):
        got = _drain_pipelined(nodes, existing, pending, tail_rounds=mode)
        all_pods = [(p, p.node_name) for p in existing] + \
            [(p, got.get(p.name)) for p in pending]
        placements = [(p, got.get(p.name)) for p in pending]
        err = _violates_required_anti(placements, nodes_by_name, all_pods)
        assert err is None, (mode, err)
        err = _violates_required_aff(placements, nodes_by_name, all_pods)
        assert err is None, (mode, err)
        # determinism incl. requeue order: overlap off is bit-identical
        got_seq = _drain_pipelined(nodes, existing, pending, overlap=False,
                                   tail_rounds=mode)
        assert got == got_seq, f"tail_rounds={mode} not deterministic"
        results[mode] = got
    bound_rounds = {k for k, v in results[True].items() if v}
    bound_scan = {k for k, v in results[False].items() if v}
    assert bound_rounds == bound_scan, \
        (bound_rounds - bound_scan, bound_scan - bound_rounds)


def test_tail_rounds_collapse_sequential_depth():
    """The point of the conflict-round tail: a zone co-location group of
    P pods must place in a HANDFUL of rounds (one bootstrap round + the
    fan-out), not one round per pod — and still co-locate exactly."""
    from kubernetes_tpu.utils.trace import COUNTERS

    nodes = [make_node(f"n{i:02d}", cpu=32000, memory=64 * (1 << 30),
                       pods=110, labels={"host": f"h{i}", "zone": f"z{i % 2}"})
             for i in range(10)]
    pods = []
    for i in range(48):
        p = make_pod(f"pack-{i}", cpu=100, labels={"app": "pack"})
        p.affinity = Affinity(pod_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": "pack"}),
                namespaces=[], topology_key="zone")]))
        pods.append(p)
    COUNTERS.reset()
    got = _drain_pipelined(nodes, [], pods, chunk=48, tail_rounds=True)
    snap = COUNTERS.snapshot()
    assert all(got[p.name] for p in pods), got
    zones = {int(got[p.name][1:]) % 2 for p in pods}
    assert len(zones) == 1, f"group split across zones: {zones}"
    rounds = snap.get("engine.tail_rounds", (0, 0))[0]
    dispatches = snap.get("engine.tail_round_dispatch", (0, 0))[0]
    assert dispatches >= 1, snap
    # 48 pods through the tail in a handful of rounds: bootstrap +
    # fan-out (+ the final empty retire round), NOT one per pod
    assert 0 < rounds <= 8, (rounds, snap)


def test_pipelined_fuzz_oracle_under_sanitizer(monkeypatch, seed=5):
    """ISSUE 4 satellite: one wave-vs-strict-oracle fuzz case with every
    upload seam armed (GRAFT_SANITIZE=1 — copy seams alias-asserted,
    static bundles frozen). The sanitizer must catch nothing on the
    current tree, the oracle invariants must hold, and placements must be
    bit-identical to the unsanitized drain — proving the sanitizer is an
    observer, not a participant. The CONFLICT-ROUND tail is forced on
    (ISSUE 5 acceptance: the new tail path too must be sanitize-inert)."""
    rng = random.Random(seed)
    nodes, existing = _build_pipeline_cluster(rng)
    for i, n in enumerate(nodes):
        n.labels.setdefault("host", f"h{i}")
    pending = _pending_required_mix(rng, 18)
    got_ref = _drain_pipelined(nodes, existing, pending, tail_rounds=True)

    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    got = _drain_pipelined(nodes, existing, pending, tail_rounds=True)
    assert got == got_ref, "sanitizer changed placements"
    nodes_by_name = {n.name: n for n in nodes}
    all_pods = [(p, p.node_name) for p in existing] + \
        [(p, got.get(p.name)) for p in pending]
    placements = [(p, got.get(p.name)) for p in pending]
    err = _violates_required_anti(placements, nodes_by_name, all_pods)
    assert err is None, err
    err = _violates_required_aff(placements, nodes_by_name, all_pods)
    assert err is None, err
