"""Round-5 integration: the new subsystems working TOGETHER in one
cluster — a mutating admission webhook stamps pods at create, the
scheduler binds them, a CRI-backed kubelet with a node-allocatable
reservation runs them, and the CLI's diff/patch drive a change — the
cross-subsystem wiring no per-component test exercises."""

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.admission.chain import AdmissionChain, default_plugins
from kubernetes_tpu.admission.webhook import (
    GenericAdmissionWebhook,
    Rule,
    WebhookHook,
)
from kubernetes_tpu.api.types import Resource, make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.nodes.kubelet import HollowKubelet
from kubernetes_tpu.server.apiserver import ApiServer


class StampingWebhook:
    """Mutating backend: every pod gets an injected audit label."""

    def __init__(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(length))
                obj = dict(review["request"]["object"])
                obj["metadata"] = dict(obj["metadata"])
                labels = dict(obj["metadata"].get("labels") or {})
                labels["audit/stamped"] = "true"
                obj["metadata"]["labels"] = labels
                body = json.dumps({"response": {
                    "allowed": True, "patchedObject": obj}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._srv.server_address[1]}/admit"
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_webhook_scheduler_cri_cli_together(tmp_path):
    backend = StampingWebhook()
    try:
        api = ApiServer()
        api.admission = AdmissionChain(
            default_plugins() + [GenericAdmissionWebhook([WebhookHook(
                name="stamper", url=backend.url, mutating=True,
                rules=[Rule(operations=["CREATE"], kinds=["Pod"])])])],
            store=api.store)
        api.store.create("Namespace", Namespace("default"))

        # a reserved node: capacity 2000m, 300m held back
        kubelet = HollowKubelet(
            api.store, make_node("n0", cpu=2000, memory=4 << 30),
            reserved=Resource(milli_cpu=300))
        kubelet.register()
        assert api.store.get("Node", "", "n0") \
            .allocatable.milli_cpu == 1700

        sched = Scheduler(api.store, record_events=False)
        sched.start()

        # create THROUGH the chain: the webhook stamps, scheduler binds,
        # the CRI kubelet runs it
        api.create("Pod", make_pod("web", cpu=200, memory=256 << 20))
        sched.run_until_drained()
        pod = api.store.get("Pod", "default", "web")
        assert pod.labels.get("audit/stamped") == "true"  # webhook ran
        assert pod.node_name == "n0"  # scheduler bound
        kubelet.handle_pod(pod)
        kubelet.step()
        assert api.store.get("Pod", "default", "web").phase == "Running"
        assert kubelet.runtime.ops.get("RunPodSandbox") == 1  # CRI ran it

        # the CLI previews then patches the running pod
        out = io.StringIO()
        kt = Ktctl(api, out=out)
        patch = json.dumps({"metadata": {"labels": {"tier": "fe"}}})
        assert kt.run(["patch", "pod", "web", "-p", patch]) == 0
        p = api.store.get("Pod", "default", "web")
        assert p.labels.get("tier") == "fe"
        assert p.labels.get("audit/stamped") == "true"  # stamp survives
        assert p.phase == "Running"  # patch preserved status
        assert p.node_name == "n0"  # and the binding
    finally:
        backend.stop()
