"""PodPriority + preemption (feature-gated).

Reference: the PodPriority gate is v1.7 (kube_features.go:122, alpha);
the preemption design implemented is 1.8's scheduler preemption
(generic_scheduler.go Preempt / selectVictimsOnNode /
pickOneNodeForPreemption). Pinned:
- gate off: strict FIFO queue, no preemption (1.7 default behavior);
- gate on: higher-priority pods pop first; an unschedulable
  high-priority pod evicts a minimal, lowest-priority victim set on the
  node chosen by (max victim prio, sum victim prio, count);
- equal/higher-priority pods are never victims;
- the preemptor lands on the freed node in a following round,
  end-to-end through the batch engine.
"""

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.preemption import pick_preemption
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.node_info import NodeInfo
from kubernetes_tpu.utils import features

Mi = 1 << 20
Gi = 1 << 30


@pytest.fixture()
def pod_priority():
    features.DEFAULT_FEATURE_GATE.set("PodPriority", True)
    yield
    features.DEFAULT_FEATURE_GATE.reset()


def prio_pod(name, priority, cpu=100, node_name=""):
    p = make_pod(name, cpu=cpu, memory=64 * Mi, node_name=node_name)
    p.priority = priority
    return p


def info_with(node, *pods):
    info = NodeInfo(node)
    for p in pods:
        info.add_pod(p)
    return info


# ------------------------------------------------------------ pick/victims


def test_pick_preemption_minimal_victims():
    node = make_node("n1", cpu=1000, memory=8 * Gi)
    infos = {"n1": info_with(node,
                             prio_pod("low-a", 1, cpu=400, node_name="n1"),
                             prio_pod("low-b", 2, cpu=400, node_name="n1"),
                             prio_pod("hi", 100, cpu=200, node_name="n1"))}
    plan = pick_preemption(prio_pod("pre", 50, cpu=400), infos)
    assert plan is not None and plan.node_name == "n1"
    # one victim suffices; the lowest-priority one is chosen (low-a
    # reprieve order re-adds higher priorities first)
    assert [v.name for v in plan.victims] == ["low-a"]


def test_pick_preemption_prefers_cheapest_node():
    n1 = make_node("n1", cpu=1000, memory=8 * Gi)
    n2 = make_node("n2", cpu=1000, memory=8 * Gi)
    infos = {
        # evicting on n1 costs a priority-10 pod
        "n1": info_with(n1, prio_pod("v10", 10, cpu=900, node_name="n1")),
        # evicting on n2 costs a priority-2 pod — cheaper
        "n2": info_with(n2, prio_pod("v2", 2, cpu=900, node_name="n2")),
    }
    plan = pick_preemption(prio_pod("pre", 50, cpu=500), infos)
    assert plan.node_name == "n2"
    assert [v.name for v in plan.victims] == ["v2"]


def test_no_preemption_against_equal_or_higher_priority():
    node = make_node("n1", cpu=1000, memory=8 * Gi)
    infos = {"n1": info_with(node,
                             prio_pod("same", 50, cpu=900, node_name="n1"))}
    assert pick_preemption(prio_pod("pre", 50, cpu=500), infos) is None
    assert pick_preemption(prio_pod("pre0", 0, cpu=500), infos) is None


def test_infeasible_even_with_all_victims_gone():
    node = make_node("n1", cpu=400, memory=8 * Gi)
    infos = {"n1": info_with(node,
                             prio_pod("low", 1, cpu=300, node_name="n1"))}
    # needs 500m on a 400m node: no amount of eviction helps
    assert pick_preemption(prio_pod("pre", 50, cpu=500), infos) is None


# ----------------------------------------------------------- queue ordering


def test_queue_fifo_without_gate():
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=10_000, memory=8 * Gi))
    sched = Scheduler(api)
    sched.start()
    for name, pr in (("a", 0), ("b", 100), ("c", 50)):
        api.create("Pod", prio_pod(name, pr))
    sched.sync()
    popped = sched.queue.pop_batch()
    assert [p.name for p in popped] == ["a", "b", "c"]  # strict FIFO


def test_queue_priority_order_with_gate(pod_priority):
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=10_000, memory=8 * Gi))
    sched = Scheduler(api)
    sched.start()
    for name, pr in (("a", 0), ("b", 100), ("c", 50), ("d", 100)):
        api.create("Pod", prio_pod(name, pr))
    sched.sync()
    popped = sched.queue.pop_batch()
    # priority desc, FIFO within a band
    assert [p.name for p in popped] == ["b", "d", "c", "a"]


# ------------------------------------------------------------- end to end


def test_preemption_end_to_end(pod_priority):
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=1000, memory=8 * Gi))
    sched = Scheduler(api)
    sched.start()
    # fill the node with low-priority pods
    for i in range(4):
        api.create("Pod", prio_pod(f"low-{i}", 1, cpu=250))
    sched.run_until_drained()
    assert all(p.node_name for p in api.list("Pod")[0])
    # a high-priority pod arrives; no room
    api.create("Pod", prio_pod("critical", 1000, cpu=500))
    stats = sched.schedule_round()
    assert stats["unschedulable"] == 1
    assert stats.get("preemptions") == 1
    # victims evicted (two 250m pods must go for 500m)
    remaining = api.list("Pod")[0]
    lows = [p for p in remaining if p.name.startswith("low-")]
    assert len(lows) == 2
    evs = [e for e in sched.events if e.reason == "Preempted"]
    assert len(evs) == 2
    # the preemptor schedules on a following round (backoff may defer it)
    import time as _time
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        sched.schedule_round()
        crit = api.get("Pod", "default", "critical")
        if crit.node_name:
            break
        _time.sleep(0.05)
    assert api.get("Pod", "default", "critical").node_name == "n1"


def test_no_preemption_when_gate_off():
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=1000, memory=8 * Gi))
    sched = Scheduler(api)
    sched.start()
    for i in range(4):
        api.create("Pod", prio_pod(f"low-{i}", 1, cpu=250))
    sched.run_until_drained()
    api.create("Pod", prio_pod("critical", 1000, cpu=500))
    stats = sched.schedule_round()
    assert stats["unschedulable"] == 1
    assert stats["preemptions"] == 0
    assert len([p for p in api.list("Pod")[0]
                if p.name.startswith("low-")]) == 4


def test_priority_admission_resolves_class(pod_priority):
    from kubernetes_tpu.api.workloads import Namespace, PriorityClass
    from kubernetes_tpu.server.apiserver import ApiServer

    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    api.store.create("PriorityClass",
                     PriorityClass("high", value=10_000))
    p = make_pod("p", cpu=10, memory=Mi)
    p.priority_class = "high"
    api.create("Pod", p)
    assert api.get("Pod", "default", "p").priority == 10_000


def test_two_preemptors_do_not_over_evict_same_node(pod_priority):
    """Finding regression: preemptor A's freed capacity must be reserved
    in the round-local view so preemptor B doesn't plan into the same
    hole and evict extra victims."""
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=1000, memory=8 * Gi))
    api.create("Node", make_node("n2", cpu=1000, memory=8 * Gi))
    sched = Scheduler(api)
    sched.start()
    for i in range(4):
        api.create("Pod", prio_pod(f"low-{i}", 1, cpu=500))
    sched.run_until_drained()
    # two preemptors, each needs 500m: must spread over BOTH nodes,
    # evicting exactly one victim each (not two off one node)
    api.create("Pod", prio_pod("crit-a", 1000, cpu=500))
    api.create("Pod", prio_pod("crit-b", 900, cpu=500))
    stats = sched.schedule_round()
    assert stats["preemptions"] == 2
    lows = [p for p in api.list("Pod")[0] if p.name.startswith("low-")]
    # exactly two victims total — without the round-local reservation the
    # second preemptor re-plans the first one's hole and a third victim
    # dies for nothing
    assert len(lows) == 2
    import time as _time
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        sched.schedule_round()
        crits = [p for p in api.list("Pod")[0]
                 if p.name.startswith("crit-") and p.node_name]
        if len(crits) == 2:
            break
        _time.sleep(0.05)
    assert len([p for p in api.list("Pod")[0]
                if p.name.startswith("crit-") and p.node_name]) == 2


def test_preemption_respects_anti_affinity(pod_priority):
    """Finding regression: a preemptor blocked by anti-affinity against a
    HIGHER-priority pod must not evict lower-priority pods — the eviction
    would free nothing (pick_preemption now verifies with the full
    SchedulingContext, not resources alone)."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
    )

    api = ApiServerLite()
    node = make_node("n1", cpu=2000, memory=8 * Gi)
    node.labels["kubernetes.io/hostname"] = "n1"
    api.create("Node", node)
    sched = Scheduler(api)
    sched.start()
    blocker = prio_pod("blocker", 2000, cpu=100)
    blocker.labels["app"] = "db"
    api.create("Pod", blocker)
    for i in range(2):
        api.create("Pod", prio_pod(f"low-{i}", 1, cpu=900))
    sched.run_until_drained()
    assert all(p.node_name for p in api.list("Pod")[0])
    # preemptor anti-affine to the priority-2000 blocker on the only node
    pre = prio_pod("pre", 500, cpu=900)
    pre.affinity = Affinity(pod_anti_affinity=PodAffinity(required_terms=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": "db"}),
            topology_key="kubernetes.io/hostname")]))
    api.create("Pod", pre)
    stats = sched.schedule_round()
    assert stats["unschedulable"] == 1
    # NO preemption: evicting low-priority pods cannot cure the
    # anti-affinity against the higher-priority blocker
    assert stats["preemptions"] == 0
    assert len([p for p in api.list("Pod")[0]
                if p.name.startswith("low-")]) == 2


def test_preemption_fuzz_invariants(pod_priority):
    """Randomized clusters; invariants that must hold on every trial:
    - no victim ever has priority >= its preemptor's;
    - a planned node really fits the preemptor once victims leave
      (verified against the exact oracle);
    - the victim set is minimal: removing any single victim from the
      eviction leaves the preemptor unfittable (no over-eviction)."""
    import numpy as np

    from kubernetes_tpu.ops import oracle

    rng = np.random.default_rng(42)
    for trial in range(15):
        n_nodes = int(rng.integers(2, 8))
        infos = {}
        for i in range(n_nodes):
            node = make_node(f"n{i}", cpu=int(rng.integers(500, 2000)),
                             memory=8 * Gi)
            info = NodeInfo(node)
            for j in range(int(rng.integers(0, 5))):
                info.add_pod(prio_pod(
                    f"v{i}-{j}", int(rng.integers(0, 100)),
                    cpu=int(rng.integers(50, 600)), node_name=f"n{i}"))
            infos[f"n{i}"] = info
        pre = prio_pod("pre", int(rng.integers(1, 200)),
                       cpu=int(rng.integers(100, 1200)))
        plan = pick_preemption(pre, infos)
        if plan is None:
            continue
        assert all(v.priority < pre.priority for v in plan.victims), trial
        info = infos[plan.node_name]
        victims = {v.key() for v in plan.victims}

        def fits_without(excluded):
            base = NodeInfo(info.node)
            for p in info.pods:
                if p.key() not in excluded:
                    base.add_pod(p)
            return oracle.pod_fits(pre, base)

        assert fits_without(victims), f"trial {trial}: plan does not fit"
        for v in plan.victims:
            assert not fits_without(victims - {v.key()}), \
                f"trial {trial}: victim {v.name} was unnecessary"


def test_bounded_candidates_prefer_cheapest_victims(pod_priority):
    """Finding regression: with more candidates than the verification
    budget, the kept subset must be the LOWEST-max-victim-priority nodes
    (the seg_max ordering), not the first N by name."""
    import numpy as np

    from kubernetes_tpu.engine import preemption as pm

    old = pm.MAX_VERIFIED_CANDIDATES
    pm.MAX_VERIFIED_CANDIDATES = 2
    try:
        infos = {}
        # names sort so the EXPENSIVE nodes come first alphabetically
        for i, prio in enumerate([90, 90, 90, 1, 1]):
            node = make_node(f"n{i}", cpu=1000, memory=8 * Gi)
            info = NodeInfo(node)
            info.add_pod(prio_pod(f"v{i}", prio, cpu=900,
                                  node_name=f"n{i}"))
            infos[f"n{i}"] = info
        plan = pick_preemption(prio_pod("pre", 100, cpu=500), infos)
        assert plan is not None
        # must land on a priority-1 victim node despite the budget of 2
        assert plan.victims[0].priority == 1, plan
    finally:
        pm.MAX_VERIFIED_CANDIDATES = old


def test_truncation_keeps_mixed_priority_node_with_cheapest_victim(
        pod_priority):
    """Finding regression: a node holding BOTH a high- and a low-priority
    pod (where only the low one needs evicting) must survive truncation —
    ranking is by the per-node MIN below-priority pod, the floor of the
    achievable choice key."""
    from kubernetes_tpu.engine import preemption as pm

    old = pm.MAX_VERIFIED_CANDIDATES
    pm.MAX_VERIFIED_CANDIDATES = 2
    try:
        infos = {}
        # node "a-mixed": prio-89 pod (500m) + prio-1 pod (500m); evicting
        # just the prio-1 pod fits the 400m preemptor -> best key max=1
        node = make_node("a-mixed", cpu=1000, memory=8 * Gi)
        info = NodeInfo(node)
        info.add_pod(prio_pod("hi", 89, cpu=500, node_name="a-mixed"))
        info.add_pod(prio_pod("cheap", 1, cpu=500, node_name="a-mixed"))
        infos["a-mixed"] = info
        # filler nodes each with one prio-50 victim
        for i in range(4):
            n = make_node(f"b{i}", cpu=1000, memory=8 * Gi)
            fi = NodeInfo(n)
            fi.add_pod(prio_pod(f"mid{i}", 50, cpu=900, node_name=f"b{i}"))
            infos[f"b{i}"] = fi
        plan = pick_preemption(prio_pod("pre", 100, cpu=400), infos)
        assert plan is not None and plan.node_name == "a-mixed"
        assert [v.name for v in plan.victims] == ["cheap"]
    finally:
        pm.MAX_VERIFIED_CANDIDATES = old


def test_truncation_tight_bound_skips_useless_tiny_victims(pod_priority):
    """Dual-failure regression: a node whose tiny prio-1 pod cannot free
    enough space must NOT crowd out a node with a real cheap plan — the
    tight bound (prefix sums until the preemptor fits) sees through it."""
    from kubernetes_tpu.engine import preemption as pm

    old = pm.MAX_VERIFIED_CANDIDATES
    pm.MAX_VERIFIED_CANDIDATES = 2
    try:
        infos = {}
        # A-nodes: a 10m prio-1 pod (useless) + a 900m prio-90 pod; any
        # valid eviction must include the prio-90 pod -> true key 90
        for i in range(8):
            n = make_node(f"a{i}", cpu=1000, memory=8 * Gi)
            fi = NodeInfo(n)
            fi.add_pod(prio_pod(f"tiny{i}", 1, cpu=10, node_name=f"a{i}"))
            fi.add_pod(prio_pod(f"big{i}", 90, cpu=900, node_name=f"a{i}"))
            infos[f"a{i}"] = fi
        # node z: a single prio-50 victim frees everything -> true key 50
        n = make_node("z", cpu=1000, memory=8 * Gi)
        fi = NodeInfo(n)
        fi.add_pod(prio_pod("mid", 50, cpu=900, node_name="z"))
        infos["z"] = fi
        plan = pick_preemption(prio_pod("pre", 100, cpu=800), infos)
        assert plan is not None and plan.node_name == "z", plan
        assert [v.priority for v in plan.victims] == [50]
    finally:
        pm.MAX_VERIFIED_CANDIDATES = old
