"""PodSecurityPolicy admission + securitycontext resolution.

Reference targets: plugin/pkg/admission/security/podsecuritypolicy/
admission.go (try policies in order, first validating wins, mutate +
annotate), pkg/security/podsecuritypolicy strategies (RunAsAny /
MustRunAs / MustRunAsNonRoot, host ports, volumes FSTypes, privileged,
readOnlyRootFilesystem), pkg/securitycontext (container overrides pod).
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.admission.chain import (
    AdmissionChain,
    AdmissionRequest,
    CREATE,
    Rejected,
    default_plugins,
)
from kubernetes_tpu.admission.plugins import PodSecurityPolicyPlugin
from kubernetes_tpu.api.types import (
    PodSecurityContext,
    SecurityContext,
    Volume,
    VolumeKind,
    make_pod,
)
from kubernetes_tpu.security import securitycontext as sc
from kubernetes_tpu.security.psp import (
    MUST_RUN_AS,
    MUST_RUN_AS_NON_ROOT,
    PSP_ANNOTATION,
    PSP_KIND,
    PodSecurityPolicy,
    Provider,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


# ------------------------------------------------------- securitycontext


def test_container_overrides_pod_security_context():
    pod = make_pod("p")
    pod.security_context = PodSecurityContext(run_as_user=1000,
                                              run_as_non_root=True)
    c = pod.containers[0]
    assert sc.effective_run_as_user(pod, c) == 1000
    assert sc.effective_run_as_non_root(pod, c) is True
    c.security_context = SecurityContext(run_as_user=0,
                                         run_as_non_root=False)
    assert sc.effective_run_as_user(pod, c) == 0
    assert sc.effective_run_as_non_root(pod, c) is False


# ----------------------------------------------------------- provider


def test_privileged_gate():
    pod = make_pod("p")
    pod.containers[0].security_context = SecurityContext(privileged=True)
    assert Provider(PodSecurityPolicy("restricted")).validate(pod)
    assert not Provider(
        PodSecurityPolicy("priv", privileged=True)).validate(pod)


def test_host_network_gate():
    pod = make_pod("p")
    pod.host_network = True
    assert Provider(PodSecurityPolicy("restricted")).validate(pod)
    assert not Provider(
        PodSecurityPolicy("hostnet", host_network=True)).validate(pod)


def test_host_port_ranges():
    pod = make_pod("p", ports=[8080])
    assert Provider(PodSecurityPolicy("none")).validate(pod)
    assert Provider(PodSecurityPolicy(
        "low", host_ports=[(1, 1024)])).validate(pod)
    assert not Provider(PodSecurityPolicy(
        "web", host_ports=[(8000, 9000)])).validate(pod)


def test_volume_fstypes():
    pod = make_pod("p", volumes=[
        Volume(name="v", kind=VolumeKind.GCE_PD, volume_id="d1")])
    assert not Provider(PodSecurityPolicy("any")).validate(pod)  # "*"
    assert not Provider(PodSecurityPolicy(
        "pd-only", volumes=["GCEPersistentDisk"])).validate(pod)
    errs = Provider(PodSecurityPolicy(
        "none", volumes=["Other"])).validate(pod)
    assert errs and "GCEPersistentDisk" in errs[0]


def test_must_run_as_non_root():
    psp = PodSecurityPolicy("nonroot",
                            run_as_user_rule=MUST_RUN_AS_NON_ROOT)
    root = make_pod("root")
    root.containers[0].security_context = SecurityContext(run_as_user=0)
    assert Provider(psp).validate(root)
    unset = make_pod("unset")  # neither uid nor runAsNonRoot: reject
    assert Provider(psp).validate(unset)
    marked = make_pod("marked")
    marked.security_context = PodSecurityContext(run_as_non_root=True)
    assert not Provider(psp).validate(marked)
    uid = make_pod("uid")
    uid.containers[0].security_context = SecurityContext(run_as_user=100)
    assert not Provider(psp).validate(uid)


def test_must_run_as_defaults_and_validates_range():
    psp = PodSecurityPolicy("ranged", run_as_user_rule=MUST_RUN_AS,
                            run_as_user_ranges=[(1000, 2000)])
    pod = make_pod("p")
    out = Provider(psp).apply_defaults(pod)
    assert pod.security_context is None  # input untouched
    assert out.security_context.run_as_user == 1000  # range min assigned
    assert not Provider(psp).validate(out)
    bad = make_pod("bad")
    bad.security_context = PodSecurityContext(run_as_user=5)
    assert Provider(psp).validate(Provider(psp).apply_defaults(bad))


def test_read_only_root_filesystem_required():
    psp = PodSecurityPolicy("ro", read_only_root_filesystem=True)
    pod = make_pod("p")
    assert Provider(psp).validate(pod)
    pod.containers[0].security_context = SecurityContext(
        read_only_root_filesystem=True)
    assert not Provider(psp).validate(pod)


# ----------------------------------------------------------- admission


def _store():
    from kubernetes_tpu.api.workloads import Namespace
    store = ApiServerLite()
    store.create("Namespace", Namespace("default"))
    return store


def _chain_with_psp(store):
    return AdmissionChain(default_plugins() + [PodSecurityPolicyPlugin()],
                          store=store)


def _admit_pod(chain, pod):
    req = AdmissionRequest(operation=CREATE, kind="Pod",
                           namespace=pod.namespace, name=pod.name,
                           obj=pod)
    chain.admit(req)
    return pod


def test_admission_first_policy_by_name_wins_and_annotates():
    store = _store()
    store.create(PSP_KIND, PodSecurityPolicy(
        "a-ranged", run_as_user_rule=MUST_RUN_AS,
        run_as_user_ranges=[(1000, 2000)]))
    store.create(PSP_KIND, PodSecurityPolicy("b-anything",
                                             privileged=True))
    chain = _chain_with_psp(store)
    pod = _admit_pod(chain, make_pod("p"))
    assert pod.annotations[PSP_ANNOTATION] == "a-ranged"
    assert pod.security_context.run_as_user == 1000  # mutation committed


def test_admission_falls_through_to_permissive_policy():
    store = _store()
    store.create(PSP_KIND, PodSecurityPolicy("a-restricted"))
    store.create(PSP_KIND, PodSecurityPolicy("b-priv", privileged=True))
    chain = _chain_with_psp(store)
    pod = make_pod("p")
    pod.containers[0].security_context = SecurityContext(privileged=True)
    _admit_pod(chain, pod)
    assert pod.annotations[PSP_ANNOTATION] == "b-priv"


def test_admission_rejects_when_nothing_validates():
    store = _store()
    store.create(PSP_KIND, PodSecurityPolicy("restricted"))
    chain = _chain_with_psp(store)
    pod = make_pod("p")
    pod.host_network = True
    with pytest.raises(Rejected, match="hostNetwork"):
        _admit_pod(chain, pod)


def test_admission_rejects_with_no_policies():
    chain = _chain_with_psp(_store())
    with pytest.raises(Rejected, match="no policies defined"):
        _admit_pod(chain, make_pod("p"))


def test_default_chain_without_psp_plugin_still_admits():
    """PSP is opt-in (not in the 1.7 recommended set) — the default chain
    must not start rejecting pods."""
    chain = AdmissionChain(default_plugins(), store=_store())
    pod = make_pod("p")
    pod.host_network = True
    _admit_pod(chain, pod)  # no exception


def test_full_apiserver_with_psp_end_to_end():
    """Through the real handler chain: POST pod -> authn -> admission(PSP)
    -> registry -> store, both accept and reject paths."""
    from kubernetes_tpu.server.apiserver import ApiServer

    from kubernetes_tpu.api.workloads import Namespace

    srv = ApiServer(auth=False)
    srv.store.create("Namespace", Namespace("default"))
    srv.admission.plugins.append(PodSecurityPolicyPlugin())
    for plug in srv.admission.plugins:
        if hasattr(plug, "set_store"):
            plug.set_store(srv.store)
    srv.create(PSP_KIND, PodSecurityPolicy(
        "default", host_ports=[(8000, 9000)]))
    ok = srv.create("Pod", make_pod("web", ports=[8080]))
    stored = srv.get("Pod", "default", "web")
    assert stored.annotations[PSP_ANNOTATION] == "default"
    with pytest.raises(Rejected):
        srv.create("Pod", make_pod("bad", ports=[22]))


def test_manifest_wire_format_carries_security_fields():
    """Regression (review): a k8s JSON manifest's hostNetwork and
    securityContext must survive decode (else PSP enforcement is bypassed
    for REST-submitted pods) and re-encode."""
    from kubernetes_tpu.api import serde

    manifest = {
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {
            "hostNetwork": True,
            "securityContext": {"runAsUser": 1000, "runAsNonRoot": True},
            "containers": [{
                "name": "c0",
                "securityContext": {"privileged": True, "runAsUser": 0,
                                    "readOnlyRootFilesystem": True},
            }],
        },
    }
    pod = serde.decode_pod(manifest)
    assert pod.host_network is True
    assert pod.security_context.run_as_user == 1000
    assert pod.security_context.run_as_non_root is True
    csc = pod.containers[0].security_context
    assert csc.privileged is True and csc.run_as_user == 0
    assert csc.read_only_root_filesystem is True
    # PSP actually sees the decoded fields
    assert Provider(PodSecurityPolicy("restricted")).validate(pod)
    # and the round-trip preserves them
    enc = serde.encode_pod(pod)
    again = serde.decode_pod(enc)
    assert again.host_network is True
    assert again.containers[0].security_context.privileged is True
    assert again.security_context.run_as_user == 1000


def test_psp_kind_decodes_over_the_wire():
    """Regression (review): the podsecuritypolicies REST route must be able
    to decode a PSP body (wire.KIND_REGISTRY entry)."""
    from kubernetes_tpu.api import wire

    obj = wire.decode_any(
        {"name": "restricted", "privileged": False,
         "host_ports": [[8000, 9000]],
         "run_as_user_rule": "MustRunAsNonRoot"},
        kind=PSP_KIND)
    assert isinstance(obj, PodSecurityPolicy)
    assert obj.run_as_user_rule == "MustRunAsNonRoot"
