"""Pod-level black box (ISSUE 15): sampled lifecycle tracing, typed
fence reasons, SLO burn rates, trace-context transport parity, the
trend reader.

Pins the contracts the tentpole rests on:

- the tracer is an EXACT no-op off; head sampling is deterministic
  (crc32) and the live map / exemplar reservoir / per-timeline event
  lists stay bounded under a 500k-pod offer;
- phase decomposition TELESCOPES: per-pod phase sums equal the pod's
  first-event->BOUND span exactly (the tail-forensics acceptance);
- fence requeues carry typed reasons (capacity here; the per-reason
  counters partition the folded count exactly);
- one trace context joins filter->bind hops on HTTP, the binary wire
  and the embedded API into timelines of IDENTICAL shape, and the
  /debug/pods + /debug/slo views are byte-identical across all three
  transports;
- the exactly-once audit holds under the churn + injected-fault storm:
  no duplicate BOUND events, every completed timeline matches a
  store-bound pod;
- SLO burn-rate math: under-budget streams burn ~0, a sustained breach
  alerts once (flip recorded on the flight-recorder ring) and recovers;
- bench.py --trend flags a seeded synthetic regression with a nonzero
  exit and stays quiet inside the noise band.
"""

from __future__ import annotations

import json
import threading

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.observability import podtrace as pt
from kubernetes_tpu.observability import trend
from kubernetes_tpu.observability.podtrace import TRACER, PodTracer
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.observability.slo import SLO, SLOMonitor
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.trace import COUNTERS

Gi = 1 << 30


@pytest.fixture
def tracer():
    """The process-wide tracer armed at sample=1 for one test and ALWAYS
    disarmed after — global state must never leak across tests."""
    TRACER.clear()
    old_sample, old_mask = TRACER.sample, TRACER._mask
    TRACER.sample, TRACER._mask = 1, 0
    TRACER.enable()
    try:
        yield TRACER
    finally:
        TRACER.disable()
        TRACER.sample, TRACER._mask = old_sample, old_mask
        TRACER.clear()


@pytest.fixture
def slo():
    SLO.clear()
    SLO.enable()
    try:
        yield SLO
    finally:
        SLO.disable()
        SLO.clear()


def mk_sched(nodes, pods, chunk=64):
    api = ApiServerLite()
    load_cluster(api, nodes, pods)
    s = Scheduler(api, record_events=False)
    s.pipeline_chunk = chunk
    s.start()
    return api, s


# ------------------------------------------------------------ off = no-op


def test_tracer_off_is_exact_noop():
    assert not TRACER.enabled
    before = TRACER.stats()
    api, s = mk_sched(hollow_nodes(16), PROFILES["density"](200))
    s.run_until_drained(max_batch=64)
    after = TRACER.stats()
    assert after["sampled_total"] == before["sampled_total"]
    assert after["completed_total"] == before["completed_total"]


# --------------------------------------------------------------- sampling


def test_head_sampling_deterministic_and_near_rate():
    t = PodTracer(sample=64, max_live=1 << 20, window_s=3600)
    keys = [f"ns/pod-{i:06d}" for i in range(40_000)]
    hits = [k for k in keys if t.sampled(k)]
    # crc32 is uniform: 40k keys at 1-in-64 -> ~625 expected
    assert 380 <= len(hits) <= 900, len(hits)
    assert hits == [k for k in keys if t.sampled(k)]  # deterministic
    t1 = PodTracer(sample=64, window_s=3600)
    assert [k for k in keys[:2000] if t1.sampled(k)] == \
        [k for k in keys[:2000] if t.sampled(k)]  # cross-instance too


def test_memory_bounds_under_500k_pod_offer():
    """The 500k-pod bound: live map capped at max_live with drops
    COUNTED, per-timeline events capped, exemplar heap capped at K —
    memory is O(max_live * max_events), never O(offer)."""
    t = PodTracer(sample=64, max_live=1024, exemplars=16,
                  window_s=3600.0, max_events=16)
    t.enable()
    n = 500_000
    chunk = 8192
    for lo in range(0, n, chunk):
        keys = [f"ns/p{i:07d}" for i in range(lo, min(lo + chunk, n))]
        t.begin_batch(keys)
        t.pop_batch(keys)
        # half the chunks complete, half stay live (the backlog shape)
        if (lo // chunk) % 2 == 0:
            t.bound_batch(keys)
    st = t.stats()
    assert st["live"] <= 1024
    assert len(t._heap) <= 16
    assert st["sampled_total"] + st["dropped_live"] >= n // 64 * 0.5
    assert st["dropped_live"] > 0  # the cap really engaged and counted
    # a fence-requeue loop cannot grow one timeline unboundedly
    t2 = PodTracer(sample=1, max_live=8, max_events=8, window_s=3600)
    t2.enable()
    t2.begin_batch(["ns/loop"])
    for _ in range(50):
        t2.event("ns/loop", pt.FENCE_REQUEUED, a=pt.REASON_CAPACITY)
    assert len(t2.timeline("ns/loop")) <= 8
    assert t2.stats()["dropped_events"] > 0


def test_exemplar_reservoir_keeps_slowest_k():
    clock = [0.0]
    t = PodTracer(sample=1, exemplars=4, window_s=3600,
                  now=lambda: clock[0])
    t.enable()
    for i, span in enumerate([5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0]):
        clock[0] = 100.0 * i
        t.begin_batch([f"ns/x{i}"])
        clock[0] = 100.0 * i + span
        t.bound_batch([f"ns/x{i}"])
    spans = [e["span_ms"] for e in t.snapshot()["exemplars"]]
    assert spans == [9000.0, 8000.0, 7000.0, 5000.0]  # slowest-K, desc


def test_window_rotation_and_abandonment():
    clock = [1000.0]
    t = PodTracer(sample=1, exemplars=4, window_s=10.0,
                  now=lambda: clock[0])
    t.enable()
    t.begin_batch(["ns/w1", "ns/stale"])
    clock[0] = 1001.0
    t.bound_batch(["ns/w1"])
    assert t.snapshot()["exemplars"]
    clock[0] = 1015.0  # next window
    t.begin_batch(["ns/w2"])
    clock[0] = 1016.0
    t.bound_batch(["ns/w2"])
    snap = t.snapshot()
    assert [e["key"] for e in snap["exemplars"]] == ["ns/w2"]
    assert [e["key"] for e in snap["prev_exemplars"]] == ["ns/w1"]
    # the never-completing live entry is abandoned once it predates the
    # previous window
    clock[0] = 1040.0
    snap = t.snapshot()
    assert t.stats()["abandoned"] == 1
    assert t.timeline("ns/stale") is None


def test_duplicate_bound_is_counted_and_eviction_clears_it():
    t = PodTracer(sample=1, window_s=3600)
    t.enable()
    t.begin_batch(["ns/dup"])
    t.bound_batch(["ns/dup"])
    t.bound_batch(["ns/dup"])  # second BOUND: a duplicate witness
    assert t.stats()["duplicate_bound"] == 1
    # a committed eviction clears the done-mark: the re-placement's
    # second BOUND is legitimate
    t.evicted_batch(["ns/dup"])
    t.begin_batch(["ns/dup"])
    t.bound_batch(["ns/dup"])
    assert t.stats()["duplicate_bound"] == 1  # unchanged


# ------------------------------------------------- phases + fence reasons


def test_phases_telescope_exactly_on_a_real_drain(tracer):
    api, s = mk_sched(hollow_nodes(32), PROFILES["density"](400),
                      chunk=128)
    tot = s.run_until_drained()
    assert tot["bound"] == 400
    snap = tracer.snapshot()
    assert snap["stats"]["completed_total"] == 400
    assert snap["exemplars"]
    for ex in snap["exemplars"]:
        assert abs(sum(ex["phases_ms"].values()) - ex["span_ms"]) < 1e-6
        kinds = [e["kind"] for e in ex["events"]]
        assert kinds[0] == "enqueued" and kinds[-1] == "bound"
        assert "wave_dispatched" in kinds and "harvested" in kinds
    # the window aggregate saw every completion
    agg = snap["phases"]
    assert sum(v["count"] for v in agg.values()) >= 400
    assert {"queue_wait", "dispatch", "device", "bind_flush"} <= set(agg)


def test_fence_requeue_typed_capacity_reason(tracer):
    """The blind capacity-conflict shape (test_pipeline_drain): every
    fence requeue in this scenario is a capacity race — the typed
    per-reason counters must partition the folded count exactly, and
    the requeued pods' timelines carry the reason code."""
    c0 = {n: COUNTERS.count("engine.fence_reason_" + n)
          for n in pt.REASON_NAMES}
    nodes = [make_node(f"n{i:03d}", cpu=2000, memory=8 * Gi, pods=110)
             for i in range(16)]  # each fits exactly 2 pods
    pods = [make_pod(f"p{i:03d}", cpu=1000, memory=256 << 20)
            for i in range(40)]
    api, s = mk_sched(nodes, pods, chunk=8)
    tot = s.run_until_drained()
    assert tot["bound"] == 32 and tot["fence_requeued"] > 0
    deltas = {n: COUNTERS.count("engine.fence_reason_" + n) - c0[n]
              for n in pt.REASON_NAMES}
    assert deltas["capacity"] == tot["fence_requeued"], deltas
    assert sum(deltas.values()) == tot["fence_requeued"], deltas
    # timelines of fenced pods carry the typed code — the losers of the
    # capacity race are often the pods that never bind, so look at BOTH
    # completed exemplars and still-live timelines
    codes = [e["a"] for ex in tracer.snapshot()["exemplars"]
             for e in ex["events"] if e["kind"] == "fence_requeued"]
    with tracer._lock:
        codes += [a for ev in tracer._live.values()
                  for k, _t, a, _b in ev if k == pt.FENCE_REQUEUED]
    assert codes and all(c == pt.REASON_CAPACITY for c in codes)


# -------------------------------------------------------------------- SLO


def test_slo_burn_rates_and_alert_flip_fake_clock():
    clock = [10_000.0]
    mon = SLOMonitor(budget_s=0.25, target=0.99, fast_window_s=10.0,
                     slow_window_s=40.0, bucket_s=1.0, alert_burn=5.0,
                     now=lambda: clock[0])
    mon.enable()
    RECORDER.clear()
    RECORDER.enable()
    try:
        # healthy stream: everything under budget, burn 0, no alert
        for i in range(10):
            clock[0] = 10_000.0 + i
            mon.observe_batch([0.05] * 100)
        s = mon.snapshot()
        assert s["burn_fast"] == 0.0 and s["alert"] == 0
        assert s["p99_ms"] <= 100.0
        # sustained breach: 50% of pods over budget -> burn 50/1 = 50x
        for i in range(10, 20):
            clock[0] = 10_000.0 + i
            mon.observe_batch([0.05] * 50 + [0.9] * 50)
        s = mon.snapshot()
        assert s["burn_fast"] > 5.0 and s["burn_slow"] >= 1.0
        assert s["alert"] == 1 and s["alerts_total"] == 1
        # the flip landed on the flight-recorder ring
        flips = [e for e in RECORDER.snapshot()
                 if e["kind"] == "slo_alert"]
        assert flips and flips[0]["a"] == 1
        # recovery: the breach ages out of the fast window
        for i in range(20, 35):
            clock[0] = 10_000.0 + i
            mon.observe_batch([0.05] * 100)
        s = mon.snapshot()
        assert s["alert"] == 0
        assert [e["a"] for e in RECORDER.snapshot()
                if e["kind"] == "slo_alert"] == [1, 0]
    finally:
        RECORDER.disable()
        RECORDER.clear()
        mon.disable()


def test_scheduler_feeds_slo_all_pods(slo):
    api, s = mk_sched(hollow_nodes(16), PROFILES["density"](150))
    s.run_until_drained(max_batch=64)
    snap = slo.snapshot()
    assert snap["slow_good"] + snap["slow_bad"] == 150
    assert "slo.budget_ms" in s.telemetry.snapshot()


# ------------------------------------------------- trace-context parity


def _parity_rig(n_nodes=24):
    from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
    from kubernetes_tpu.server.embedded import VerdictService
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )

    b = TPUExtenderBackend(coalesce_window_s=0.0005)
    b.sync_nodes(hollow_nodes(n_nodes))
    b.filter(make_pod("warm", cpu=100, memory=256 << 20), None, None)
    svc = VerdictService(b)
    http_srv = ExtenderHTTPServer(b)
    http_srv.start()
    bin_srv = AsyncBinaryServer(svc)
    bin_srv.start()
    return b, svc, http_srv, bin_srv


def _http_post(port, path, payload, headers=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    try:
        body = json.dumps(payload).encode()
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _http_get(port, path):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def test_trace_context_transport_parity(tracer):
    """One trace context through each transport's filter->bind hop pair:
    the resulting timelines are IDENTICAL in shape (kinds + verb codes;
    only the transport code and timestamps differ), and the
    /debug/pods + /debug/slo views are byte-identical across HTTP,
    binary STATS, and the embedded debug_snapshot."""
    from kubernetes_tpu.api import serde
    from kubernetes_tpu.client.binarywire import BinaryWireClient

    b, svc, http_srv, bin_srv = _parity_rig()
    try:
        pod = make_pod("traced", cpu=100, memory=256 << 20)
        pod_doc = serde.encode_pod(pod)
        # HTTP: header-carried context
        _http_post(http_srv.port, "/filter",
                   {"Pod": pod_doc, "Compact": True, "TopK": 4},
                   headers={"X-Pod-Trace": "trace/http"})
        resp = _http_post(http_srv.port, "/bind",
                          {"PodName": "traced", "PodNamespace": "default",
                           "PodUID": pod.uid, "Node": "hollow-node-0"},
                          headers={"X-Pod-Trace": "trace/http"})
        assert not resp.get("Error"), resp
        # binary wire: FLAG_TRACE + trace-id field
        c = BinaryWireClient("127.0.0.1", bin_srv.port).connect()
        c.filter_fused(pod, top_k=4, trace_ctx="trace/bin")
        assert c.bind("traced-b", "default", pod.uid, "hollow-node-1",
                      trace_ctx="trace/bin").ok
        c.close()
        # embedded: native trace_ctx
        svc.filter(pod, top_k=4, compact=True, trace_ctx="trace/emb")
        assert svc.bind("traced-e", "default", pod.uid, "hollow-node-2",
                        trace_ctx="trace/emb").ok

        # successful binds COMPLETE the wire-path timelines (no
        # scheduler bind path exists here to do it): read them back as
        # completed exemplars
        by_key = {ex["key"]: ex
                  for ex in tracer.snapshot()["exemplars"]}
        shapes = {}
        codes = {}
        for tid in ("trace/http", "trace/bin", "trace/emb"):
            assert tracer.timeline(tid) is None, \
                f"{tid} never completed — wire timelines must not pin " \
                "live slots"
            ex = by_key[tid]
            shapes[tid] = [(e["kind"], e["b"]) for e in ex["events"]]
            codes[tid] = {e["a"] for e in ex["events"]
                          if e["kind"] == "wire_hop"}
            assert abs(sum(ex["phases_ms"].values())
                       - ex["span_ms"]) < 1e-6
        # identical shape: CREATED, filter hop, bind hop, BOUND
        assert shapes["trace/http"] == shapes["trace/bin"] \
            == shapes["trace/emb"]
        assert shapes["trace/http"] == [
            ("created", 0), ("wire_hop", pt.HOP_FILTER),
            ("wire_hop", pt.HOP_BIND), ("bound", 0)]
        # the transport code is the ONLY difference
        assert codes["trace/http"] == {pt.WIRE_HTTP}
        assert codes["trace/bin"] == {pt.WIRE_BINARY}
        assert codes["trace/emb"] == {pt.WIRE_EMBEDDED}

        # debug views byte-identical across all three transports
        c = BinaryWireClient("127.0.0.1", bin_srv.port).connect()
        try:
            stats = c.stats(last=5)
            emb = svc.debug_snapshot(last=5)
            http_pods = _http_get(http_srv.port, "/debug/pods")
            http_slo = _http_get(http_srv.port, "/debug/slo")
            assert http_pods == stats["pods"] == emb["pods"]
            assert http_slo == stats["slo"] == emb["slo"]
            assert json.dumps(http_pods, sort_keys=True) \
                == json.dumps(emb["pods"], sort_keys=True)
        finally:
            c.close()
    finally:
        bin_srv.stop()
        http_srv.stop()


def test_embedded_schedule_one_traces_sampled_pods(tracer):
    from kubernetes_tpu.server.embedded import EmbeddedVerdictAPI

    api = EmbeddedVerdictAPI(stale_window_s=0.0)
    api.backend.sync_nodes(hollow_nodes(8))
    pod = make_pod("fleet-pod", cpu=100, memory=128 << 20)
    node, attempts = api.schedule_one(pod)
    assert node and attempts >= 1
    # the successful bind completed the timeline — it shows up as a
    # finished exemplar, not a live slot
    assert tracer.timeline(pod.key()) is None
    ex = {e["key"]: e for e in tracer.snapshot()["exemplars"]}[pod.key()]
    hops = [(e["a"], e["b"]) for e in ex["events"]
            if e["kind"] == "wire_hop"]
    assert (pt.WIRE_EMBEDDED, pt.HOP_FILTER) in hops
    assert (pt.WIRE_EMBEDDED, pt.HOP_BIND) in hops
    assert ex["events"][-1]["kind"] == "bound"


# ------------------------------------------- exactly-once under the storm


def test_exactly_once_trace_audit_under_churn_fault_storm(tracer):
    """Churn ops + injected bind failures AND landed-timeouts: the trace
    audit mirrors the store audit — no duplicate BOUND events, every
    completed timeline names a store-bound pod, and the only sampled
    bound pods WITHOUT a BOUND event are the landed-timeout ambiguities
    (bound at the store, never confirmed through the bind path)."""
    from kubernetes_tpu.testing.churn import (
        ChurnConfig,
        ChurnInjector,
        FaultyBindApi,
        make_churn_schedule,
    )

    api = ApiServerLite()
    nodes = hollow_nodes(24)
    load_cluster(api, nodes, [])
    faulty = FaultyBindApi(api, fail_rate=0.05, timeout_rate=0.03, seed=11)
    sched = Scheduler(faulty, record_events=False)
    sched.start()
    loop = sched.stream(budget_s=5.0, min_quantum=64, max_quantum=256)
    inj = ChurnInjector(faulty, make_churn_schedule(
        [n.name for n in nodes],
        ChurnConfig(seed=5, node_churn_per_min=20.0, evict_per_min_abs=6),
        duration_s=1.5))
    for i in range(600):
        api.create("Pod", make_pod(f"storm-{i:04d}", cpu=100,
                                   memory=64 << 20))
        if i % 120 == 0:
            inj.apply_until(i / 400.0)
            loop.step()
    inj.apply_until(10.0)
    import time as _time
    deadline = _time.monotonic() + 90
    while _time.monotonic() < deadline:
        loop.step()
        if loop.settled():
            break
        sched.sync(wait=0.02)
    loop.close()
    assert faulty.injected_failures > 0 or faulty.injected_timeouts > 0

    st = tracer.stats()
    assert st["duplicate_bound"] == 0, st
    store_bound = {p.key() for p in api.list("Pod")[0] if p.node_name}
    # every completed timeline is a store-bound pod (no orphan BOUND)
    with tracer._lock:
        done = set(tracer._done)
    assert done <= store_bound, (done - store_bound)
    # sampled-but-never-completed bound pods are bounded by the injected
    # landed-timeout ambiguity (bound at the store, error on the wire)
    missing = len(store_bound) - st["completed_total"]
    assert 0 <= missing <= faulty.injected_timeouts + 8, \
        (missing, faulty.injected_timeouts)


# --------------------------------------------------------------- perfetto


def test_perfetto_flow_arrows_link_wave_stages():
    from kubernetes_tpu.observability import perfetto

    events = [
        {"kind": "dispatch", "wave": 3, "t": 1.0, "dur": 0.002,
         "a": 64, "b": 0},
        {"kind": "harvest", "wave": 3, "t": 1.010, "dur": 0.001,
         "a": 60, "b": 4},
        {"kind": "bind_flush", "wave": 3, "t": 1.012, "dur": 0.003,
         "a": 60, "b": 0},
        {"kind": "dispatch", "wave": 4, "t": 1.005, "dur": 0.002,
         "a": 64, "b": 0},
    ]
    trace = perfetto.build_chrome_trace(events)
    flows = [e for e in trace["traceEvents"] if e.get("cat") == "wave"]
    w3 = [e for e in flows if e["id"] == 3]
    assert [e["ph"] for e in w3] == ["s", "t", "f"]
    assert w3[-1]["bp"] == "e"
    assert not [e for e in flows if e["id"] == 4]  # lone stage: no arrow
    # span args carry span_ms on every lane
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert all("span_ms" in e["args"] for e in spans)


def test_perfetto_pod_lanes_render_exemplars(tracer):
    from kubernetes_tpu.observability import perfetto

    api, s = mk_sched(hollow_nodes(16), PROFILES["density"](120))
    s.run_until_drained(max_batch=64)
    exemplars = tracer.snapshot()["exemplars"]
    trace = perfetto.build_chrome_trace([])
    perfetto.add_pod_lanes(trace, exemplars)
    lanes = [e for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"
             and e["args"]["name"].startswith("pod ")]
    assert len(lanes) == len(exemplars)
    pod_spans = [e for e in trace["traceEvents"]
                 if e.get("ph") == "X"
                 and e["tid"] >= perfetto.TID_POD_BASE]
    assert pod_spans
    names = {e["name"] for e in pod_spans}
    assert names <= set(pt.PHASE_NAMES), names
    assert {"queue_wait", "device"} <= names


# ------------------------------------------------------------------ trend


def _write_round(tmp_path, r, **metrics):
    doc = {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": metrics}
    (tmp_path / f"BENCH_r{r:02d}.json").write_text(json.dumps(doc))


def test_trend_flags_seeded_regression_nonzero_exit(tmp_path, capsys):
    _write_round(tmp_path, 1, value=30000.0,
                 arrival_sustained_pods_s=20000.0,
                 arrival_p99_create_to_bound_ms=120.0)
    _write_round(tmp_path, 2, value=29000.0,
                 arrival_sustained_pods_s=9000.0,   # -55%: regression
                 arrival_p99_create_to_bound_ms=125.0)
    rc = trend.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "arrival_sustained_pods_s" in out and "REGRESSIONS" in out


def test_trend_quiet_inside_noise_band(tmp_path, capsys):
    _write_round(tmp_path, 1, value=30000.0,
                 arrival_p99_create_to_bound_ms=120.0)
    _write_round(tmp_path, 2, value=24000.0,   # -20%: inside the band
                 arrival_p99_create_to_bound_ms=140.0)
    assert trend.main(["--root", str(tmp_path)]) == 0
    assert "no regressions" in capsys.readouterr().out
    # latency direction: an INCREASE past the band flags
    _write_round(tmp_path, 3, value=30000.0,
                 arrival_p99_create_to_bound_ms=250.0)
    assert trend.main(["--root", str(tmp_path)]) == 1


def test_trend_skips_missing_metrics_and_gaps(tmp_path):
    _write_round(tmp_path, 1, value=30000.0,
                 multi_frontend_pods_s=600.0)
    _write_round(tmp_path, 2, value=29000.0)  # fleet metric absent
    _write_round(tmp_path, 4, value=28000.0,  # gap + nearest-prev rule
                 multi_frontend_pods_s=550.0)
    assert trend.find_regressions(trend.load_rounds(str(tmp_path))) == []
    _write_round(tmp_path, 5, value=27000.0,
                 multi_frontend_pods_s=300.0)  # vs r04 550: -45%
    regs = trend.find_regressions(trend.load_rounds(str(tmp_path)))
    assert [g["metric"] for g in regs] == ["multi_frontend_pods_s"]
    assert regs[0]["vs_round"] == 4


# -------------------------------------------------------- registry fold


def test_registry_folds_podtrace_and_slo(tracer, slo):
    api, s = mk_sched(hollow_nodes(8), PROFILES["density"](40))
    s.run_until_drained(max_batch=32)
    snap = s.telemetry.snapshot()
    assert snap["podtrace.completed_total"] == 40
    assert snap["podtrace.duplicate_bound"] == 0
    assert any(k.startswith("podtrace.phase.") for k in snap)
    assert snap["slo.slow_good"] + snap["slo.slow_bad"] == 40
    text = s.telemetry.render_prometheus()
    assert "tpu_podtrace_completed_total" in text
    assert "tpu_slo_burn_fast" in text
