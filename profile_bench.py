"""Profiling rig for the headline bench: times each phase of the drain.

Not part of the framework; dev-only. Run: python profile_bench.py
"""
from __future__ import annotations

import os
import time

from bench import build


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")

    # warmup (compile) run
    api, sched = build(n_nodes, n_pods, profile)
    sched.run_until_drained()

    for trial in range(3):
        api, sched = build(n_nodes, n_pods, profile)
        phases = {}

        def timed(name, fn):
            def wrap(*a, **k):
                t0 = time.perf_counter()
                r = fn(*a, **k)
                phases[name] = phases.get(name, 0.0) + time.perf_counter() - t0
                return r
            return wrap

        import kubernetes_tpu.engine.scheduler_engine as SE
        import kubernetes_tpu.engine.waves as W
        import kubernetes_tpu.state.classes as CL
        from kubernetes_tpu.ops import affinity as AF

        eng = sched.engine
        sched.sync = timed("sync", sched.sync)
        sched.queue.pop_batch = timed("pop_batch", sched.queue.pop_batch)
        eng.schedule = timed("engine.schedule", eng.schedule)
        sched.api.bind_many = timed("bind_many", sched.api.bind_many)
        sched.cache.finish_bindings_bulk = timed("finish_bulk",
                                                 sched.cache.finish_bindings_bulk)
        eng.snapshot.refresh = timed("  snapshot.refresh", eng.snapshot.refresh)
        eng._nodes_on_device = timed("  nodes_on_device", eng._nodes_on_device)
        eng._run_wave = timed("  run_wave(device)", eng._run_wave)
        sched.cache.assume_pods_bulk = timed("  assume_bulk",
                                             sched.cache.assume_pods_bulk)
        orig_cb = CL.ClassBatch
        class TimedCB(orig_cb):
            def __init__(self, *a, **k):
                t0 = time.perf_counter()
                super().__init__(*a, **k)
                phases["  ClassBatch"] = phases.get("  ClassBatch", 0.0) \
                    + time.perf_counter() - t0
        SE.ClassBatch = TimedCB
        orig_ad = AF.AffinityData
        class TimedAD(orig_ad):
            def __init__(self, *a, **k):
                t0 = time.perf_counter()
                super().__init__(*a, **k)
                phases["  AffinityData"] = phases.get("  AffinityData", 0.0) \
                    + time.perf_counter() - t0
        AF.AffinityData = TimedAD

        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            t0 = time.perf_counter()
            totals = sched.run_until_drained()
            elapsed = time.perf_counter() - t0
        finally:
            gc.enable()
            gc.unfreeze()
            SE.ClassBatch = orig_cb
            AF.AffinityData = orig_ad
        print(f"trial {trial}: elapsed={elapsed:.3f}s bound={totals['bound']}")
        top = phases.pop("engine.schedule", 0.0)
        inner = sum(v for k, v in phases.items() if k.startswith("  "))
        outer = sum(v for k, v in phases.items() if not k.startswith("  "))
        for k, v in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"    {k:24s} {v*1e3:7.1f}ms")
        print(f"    {'schedule other':24s} {(top-inner)*1e3:7.1f}ms")
        print(f"    {'(unaccounted)':24s} {(elapsed-outer-top)*1e3:7.1f}ms")


if __name__ == "__main__":
    main()
