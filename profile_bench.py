"""Profiling rig for the headline bench: times each phase of the drain.

Not part of the framework; dev-only.

  python profile_bench.py             # pipelined drain attribution: spans
                                      # per phase + measured device-idle
                                      # fraction (overlap vs sequential)
  PROFILE_CLASSIC=1 python profile_bench.py
                                      # classic synchronous rounds, the
                                      # r06-era per-phase attribution
  PROFILE_EXTENDER=1 python profile_bench.py
                                      # warm extender round attribution:
                                      # where does a /filter+/prioritize
                                      # round spend its time (refresh,
                                      # pairs, encode, kernel, HTTP), from
                                      # the utils.trace.COUNTERS spans the
                                      # fast lane emits
"""
from __future__ import annotations

import os
import time

from bench import build


def profile_extender():
    """Attribute the warm extender round: in-process span times from the
    fast lane (utils/trace.py COUNTERS) vs the HTTP wall clock, over
    result-memo hits (repeat class), kernel re-evals (bind between
    requests), and encode misses (fresh class per request)."""
    import json
    import http.client

    from bench import _build_extender
    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.utils.trace import COUNTERS

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    rounds = int(os.environ.get("PROFILE_ROUNDS", 50))
    backend, srv = _build_extender(n_nodes)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

    def post(path, obj):
        body = json.dumps(obj)
        conn.request("POST", f"/scheduler/{path}", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return json.loads(resp.read())

    def run(label, make, bind_between):
        COUNTERS.reset()
        t0 = time.perf_counter()
        for i in range(rounds):
            pod = make(i)
            enc = serde.encode_pod(pod)
            post("filter", {"Pod": enc, "NodeNames": None, "Nodes": None})
            post("prioritize", {"Pod": enc, "NodeNames": None,
                                "Nodes": None})
            if bind_between:
                backend.bind(pod.name, pod.namespace, pod.uid,
                             backend.engine.snapshot.node_names[i % n_nodes])
        wall = time.perf_counter() - t0
        print(f"\n{label}: {rounds} rounds, "
              f"{wall / rounds * 1e3:.3f} ms/round wall (HTTP incl.)")
        for name, (count, secs) in sorted(COUNTERS.snapshot().items()):
            per = secs / rounds * 1e3
            print(f"    {name:32s} x{count:<6d} {secs * 1e3:8.1f}ms total"
                  f"  {per:7.3f} ms/round")

    run("steady (repeat class, result-memo hits)",
        lambda i: make_pod(f"steady-{i}", cpu=100, memory=256 << 20),
        bind_between=False)
    run("scheduleOne (bind between rounds -> kernel re-eval)",
        lambda i: make_pod(f"so-{i}", cpu=100, memory=256 << 20),
        bind_between=True)
    run("fresh class per round (encode misses)",
        lambda i: make_pod(f"fresh-{i}", cpu=100 + i, memory=256 << 20),
        bind_between=False)
    conn.close()
    srv.stop()


def profile_pipeline():
    """Attribute the PIPELINED drain (ISSUE 2): per-phase wall from the
    engine's spans + scheduler wrappers, then the measured device-idle
    story — sequential mode exposes raw device time (pipeline.device_sync:
    no host work runs inside that window), overlapped mode exposes the
    residual un-hidden wait (pipeline.device_block), and hidden fraction =
    1 - residual/raw."""
    import gc
    import time as _time

    from kubernetes_tpu.utils.trace import COUNTERS

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")
    api, sched = build(n_nodes, n_pods, profile)
    sched.run_until_drained()  # warm compile

    def run(overlap):
        api, sched = build(n_nodes, n_pods, profile)
        phases = {}

        def timed(name, fn):
            def wrap(*a, **k):
                t0 = _time.perf_counter()
                r = fn(*a, **k)
                phases[name] = phases.get(name, 0.0) \
                    + _time.perf_counter() - t0
                return r
            return wrap

        sched.sync = timed("sync(columnar)", sched.sync)
        sched.queue.pop_batch = timed("pop_batch", sched.queue.pop_batch)
        sched.api.bind_pods_bulk = timed("bind_bulk",
                                         sched.api.bind_pods_bulk)
        sched.cache.finish_bindings_bulk = timed(
            "finish_bulk", sched.cache.finish_bindings_bulk)
        COUNTERS.reset()
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            t0 = _time.perf_counter()
            totals = sched.run_until_drained(overlap=overlap)
            elapsed = _time.perf_counter() - t0
        finally:
            gc.enable()
            gc.unfreeze()
        for name, (_c, secs) in COUNTERS.snapshot().items():
            if name.startswith("pipeline."):
                phases["  " + name] = secs
        return elapsed, totals, phases

    seq_device = []
    for trial in range(3):
        elapsed, totals, phases = run(overlap=True)
        print(f"overlap trial {trial}: elapsed={elapsed:.3f}s "
              f"bound={totals['bound']} "
              f"fence_requeued={totals['fence_requeued']}")
        for k, v in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"    {k:28s} {v * 1e3:7.1f}ms")
        residual = phases.get("  pipeline.device_block", 0.0)
        print(f"    {'(residual device wait)':28s} {residual * 1e3:7.1f}ms")
    for trial in range(2):
        elapsed, totals, phases = run(overlap=False)
        dev = phases.get("  pipeline.device_sync", 0.0)
        seq_device.append((elapsed, dev))
        print(f"sequential trial {trial}: elapsed={elapsed:.3f}s raw "
              f"device={dev * 1e3:.0f}ms "
              f"(idle-if-serial={dev / elapsed * 100:.0f}% of wall)")
    if seq_device:
        el, dev = min(seq_device)
        print(f"device-idle story: sequential wall {el:.3f}s carries "
              f"{dev * 1e3:.0f}ms of exposed device wait; the overlapped "
              f"runs above show the residual (pipeline.device_block) the "
              f"pipeline failed to hide — hidden fraction = "
              f"1 - residual/raw.")


def main():
    if os.environ.get("PROFILE_EXTENDER") == "1":
        profile_extender()
        return
    if os.environ.get("PROFILE_CLASSIC") != "1":
        profile_pipeline()
        return
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")

    # warmup (compile) run
    api, sched = build(n_nodes, n_pods, profile)
    sched.run_until_drained(pipeline=False)

    for trial in range(3):
        api, sched = build(n_nodes, n_pods, profile)
        phases = {}

        def timed(name, fn):
            def wrap(*a, **k):
                t0 = time.perf_counter()
                r = fn(*a, **k)
                phases[name] = phases.get(name, 0.0) + time.perf_counter() - t0
                return r
            return wrap

        import kubernetes_tpu.engine.scheduler_engine as SE
        import kubernetes_tpu.engine.waves as W
        import kubernetes_tpu.state.classes as CL
        from kubernetes_tpu.ops import affinity as AF

        eng = sched.engine
        sched.sync = timed("sync", sched.sync)
        sched.queue.pop_batch = timed("pop_batch", sched.queue.pop_batch)
        eng.schedule = timed("engine.schedule", eng.schedule)
        sched.api.bind_many = timed("bind_many", sched.api.bind_many)
        sched.cache.finish_bindings_bulk = timed("finish_bulk",
                                                 sched.cache.finish_bindings_bulk)
        eng.snapshot.refresh = timed("  snapshot.refresh", eng.snapshot.refresh)
        eng._nodes_on_device = timed("  nodes_on_device", eng._nodes_on_device)
        eng._run_wave = timed("  run_wave(device)", eng._run_wave)
        sched.cache.assume_pods_bulk = timed("  assume_bulk",
                                             sched.cache.assume_pods_bulk)
        orig_cb = CL.ClassBatch
        class TimedCB(orig_cb):
            def __init__(self, *a, **k):
                t0 = time.perf_counter()
                super().__init__(*a, **k)
                phases["  ClassBatch"] = phases.get("  ClassBatch", 0.0) \
                    + time.perf_counter() - t0
        SE.ClassBatch = TimedCB
        orig_ad = AF.AffinityData
        class TimedAD(orig_ad):
            def __init__(self, *a, **k):
                t0 = time.perf_counter()
                super().__init__(*a, **k)
                phases["  AffinityData"] = phases.get("  AffinityData", 0.0) \
                    + time.perf_counter() - t0
        AF.AffinityData = TimedAD

        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            t0 = time.perf_counter()
            totals = sched.run_until_drained(pipeline=False)
            elapsed = time.perf_counter() - t0
        finally:
            gc.enable()
            gc.unfreeze()
            SE.ClassBatch = orig_cb
            AF.AffinityData = orig_ad
        print(f"trial {trial}: elapsed={elapsed:.3f}s bound={totals['bound']}")
        top = phases.pop("engine.schedule", 0.0)
        inner = sum(v for k, v in phases.items() if k.startswith("  "))
        outer = sum(v for k, v in phases.items() if not k.startswith("  "))
        for k, v in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"    {k:24s} {v*1e3:7.1f}ms")
        print(f"    {'schedule other':24s} {(top-inner)*1e3:7.1f}ms")
        print(f"    {'(unaccounted)':24s} {(elapsed-outer-top)*1e3:7.1f}ms")


if __name__ == "__main__":
    main()
