// hostops: C++ host-side tensor-encoding kernels for the snapshot layer.
//
// The SURVEY §2 native seam: "a C++ host-side tensor snapshot encoder for
// the Go->TPU boundary". The Python snapshot (state/snapshot.py) flattens
// object state into index lists; these kernels turn them into the dense
// device-ready arrays without a Python-bytecode inner loop. Pure C ABI
// (ctypes-loadable, no CPython API): see kubernetes_tpu/native/__init__.py
// for the build-on-demand loader and the pure-Python fallbacks that keep
// every path working when no toolchain is present.
//
// Build: `make hostops` (build/Makefile) -> native/libhostops.so

#include <cstdint>
#include <cstring>

extern "C" {

// Fill the [n_nodes, words] uint32 host-port bitmap from (row, port) pairs.
// Ports outside [1, words*32-1] are ignored, like the Python writer
// (snapshot.py _write_ports_row). `bitmap` must be zeroed by the caller.
void fill_port_bitmaps(const int64_t* pairs, int64_t n_pairs,
                       uint32_t* bitmap, int64_t n_nodes, int64_t words) {
  const int64_t port_space = words * 32;
  for (int64_t i = 0; i < n_pairs; ++i) {
    const int64_t row = pairs[2 * i];
    const int64_t port = pairs[2 * i + 1];
    if (row < 0 || row >= n_nodes || port <= 0 || port >= port_space) {
      continue;
    }
    bitmap[row * words + port / 32] |=
        static_cast<uint32_t>(1u) << (port % 32);
  }
}

// Scatter 1s into an int8 [n_rows, width] multi-hot matrix from
// (row, col) pairs — the label/taint/avoid incidence builder. Out-of-range
// pairs are ignored (vocab columns beyond the padded width).
void fill_multi_hot(const int64_t* pairs, int64_t n_pairs, int8_t* out,
                    int64_t n_rows, int64_t width) {
  for (int64_t i = 0; i < n_pairs; ++i) {
    const int64_t row = pairs[2 * i];
    const int64_t col = pairs[2 * i + 1];
    if (row < 0 || row >= n_rows || col < 0 || col >= width) {
      continue;
    }
    out[row * width + col] = 1;
  }
}

// FNV-1a 64-bit over a byte buffer — the content hash the equivalence
// classes use for spec identity prehashing.
uint64_t fnv1a64(const uint8_t* data, int64_t n) {
  uint64_t h = 14695981039346656037ull;  // FNV-1a 64 offset basis
  for (int64_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 1099511628211ull;
  }
  return h;
}

}  // extern "C"
