"""Headline benchmark: batch-place the pending queue on a hollow cluster.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

Scenario (north star, BASELINE.md): 30,000 pending pods onto a 5,000-node
hollow cluster, end-to-end through the control plane — apiserver-lite create,
watch-driven queue fill, tensor snapshot, fused TPU wave placement through
the two-stage PIPELINED drain (wave k+1's device eval overlapping wave k's
columnar assume/bind/watch-drain — engine/scheduler.py), bulk bind writes,
watch confirmation.

vs_baseline is the ratio against the reference's 100 pods/s warn-level
scheduler throughput (test/integration/scheduler_perf/scheduler_test.go:35 —
the hard floor is 30 pods/s; real 1.7-era deployments sat between the two).

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_PROFILE (density|binpack|affinity|
hetero), BENCH_WARMUP=0 to skip the compile-warming run. Arrival stream
(the ISSUE 7 headline): BENCH_ARRIVAL_RATE (offered pods/s, default 20000),
BENCH_ARRIVAL_BUDGET_MS (create->bound latency budget driving micro-wave
admission, default 250), BENCH_ARRIVAL_SECONDS (offer window; default auto),
BENCH_ARRIVAL_BURST (creator max pods per wakeup; default ~4ms of rate),
BENCH_ARRIVAL_SWEEP (comma rates; "" disables), BENCH_ARRIVAL_SAT=0 to skip
the saturation search, BENCH_RECORDER_AB=0 to skip the flight-recorder
on/off A/B (ISSUE 13: the headline re-run with the recorder armed,
interleaved trials with per-arm medians — BENCH_RECORDER_AB_TRIALS,
default 2; telemetry_overhead_pct travels in the artifact).
Pod-level black box (ISSUE 15): BENCH_PODTRACE_AB=0 skips the
podtrace+SLO on/off A/B (same interleaved-medians methodology,
BENCH_PODTRACE_AB_TRIALS default 2, sampling at the tracer's default
1-in-64 rate); the ON arm's artifact carries the tail-forensics demo —
the slowest-K exemplar timelines of the 20k/s headline with per-phase
attribution summing to each pod's create->bound (attribution_exact is
asserted per exemplar). `python bench.py --trend` renders the
BENCH_r01..r17 trajectory + PROGRESS.jsonl and exits nonzero on a
headline regression past the ±30% box-noise band (CI contract;
observability/trend.py). Churn
scenario (ISSUE 8): BENCH_CHURN=0 to skip,
BENCH_CHURN_RATE (offered rate; default the arrival rate),
BENCH_CHURN_SEED, BENCH_CHURN_NODE_PCT_MIN (node churn fraction/min,
default 0.10), BENCH_CHURN_BIND_FAIL / BENCH_CHURN_BIND_TIMEOUT
(injected bind-fault rates). Priority/preemption scenario (ISSUE 14):
BENCH_PRIORITY=0 to skip, BENCH_PRIO_NODES (default 240 — sized so the
offered stream overcommits the cluster), BENCH_PRIO_RATE (default
2000), BENCH_PRIO_SECONDS (default 4), BENCH_PRIO_EVICT_FAIL /
BENCH_PRIO_EVICT_TIMEOUT (injected eviction-fault rates on the
victim-delete seam), BENCH_PRIO_EVICT_PER_MIN (disruption budget; the
scenario HARD-FAILS if any sliding window exceeds it).
Multi-frontend fleets (ISSUE 9/11):
BENCH_MULTIFRONTEND=0 to skip, BENCH_MF_CLIENTS/BENCH_MF_NODES/
BENCH_MF_STALE_MS/BENCH_MF_PODS_PER_CLIENT; every client count runs
BOTH transports (threaded HTTP `clients_*` and async binary wire
`binwire_*`) plus the in-process `inproc` and library-linked `embedded`
fleets. Wire-wall calibration: BENCH_WIRE_FLOOR=0 to skip,
BENCH_WIRE_FLOOR_CLIENTS (no-op threaded-HTTP vs async-binary floors in
`wire_floor`).
"""

from __future__ import annotations

import json
import os
import time

# persistent XLA compilation cache: a flaky remote-compile service mid-round
# costs one retry, not the round (r02 lost its number to a warmup-time
# connection refusal). Set before any jax import traces a kernel.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
try:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def interval_series(bind_events, create_log, backlog_samples,
                    interval_s: float):
    """Bucket bind/offer/backlog event streams into per-interval series of
    FULL buckets only (ISSUE 18): the trailing PARTIAL interval — the
    sliver between the last full bucket boundary and the final event — is
    returned separately instead of riding the series, where its few pods
    over a fractional width read as a rate collapse (BENCH_r19's 19-pod
    final bucket next to 1322-pod steady buckets). Rates computed as
    series[i] / interval_s are now exact for every element.

    bind_events:     [(t_rel, [keys])] per bind pass
    create_log:      [(t_rel, batch_size)] per creator burst
    backlog_samples: [(t_rel, depth)] — last sample in a bucket wins

    Returns (intervals, offered, backlog, tail) where tail is
    {"binds", "offered", "backlog", "width_s"} covering the partial
    remainder; sum(intervals) + tail["binds"] == total binds."""
    offer_end = create_log[-1][0] if create_log else 0.0
    end = max([t for t, _ in bind_events] + [offer_end]) if bind_events \
        else offer_end
    n_full = int(end / interval_s)
    intervals = [0] * n_full
    offered = [0] * n_full
    backlog = [0] * n_full
    tail = {"binds": 0, "offered": 0, "backlog": 0,
            "width_s": round(end - n_full * interval_s, 6)}
    for ts, keys in bind_events:
        b = int(ts / interval_s)
        if b < n_full:
            intervals[b] += len(keys)
        else:
            tail["binds"] += len(keys)
    for ts, n in create_log:
        b = int(ts / interval_s)
        if b < n_full:
            offered[b] += n
        else:
            tail["offered"] += n
    for ts, q in backlog_samples:
        b = int(ts / interval_s)
        if b < n_full:
            backlog[b] = q
        else:
            tail["backlog"] = q
    return intervals, offered, backlog, tail


def build(n_nodes: int, n_pods: int, profile: str):
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + n_pods)))
    nodes = hollow_nodes(n_nodes, heterogeneous=(profile == "hetero"),
                         gpu_fraction=0.3 if profile == "hetero" else 0.0,
                         taint_fraction=0.1 if profile == "hetero" else 0.0)
    pods = PROFILES[profile](n_pods)
    load_cluster(api, nodes, pods)
    sched = Scheduler(api, record_events=False)
    sched.start()
    return api, sched


def run_once(n_nodes: int, n_pods: int, profile: str):
    api, sched = build(n_nodes, n_pods, profile)
    # pipeline knobs: BENCH_PIPELINE=0 -> classic synchronous rounds;
    # BENCH_OVERLAP=0 -> pipelined dataflow, sequential execution (the A/B
    # debug mode); BENCH_CHUNK=<n> -> fixed wave size (default: auto)
    pipeline = os.environ.get("BENCH_PIPELINE", "1") != "0"
    overlap = os.environ.get("BENCH_OVERLAP", "1") != "0"
    chunk = int(os.environ.get("BENCH_CHUNK", "0"))
    t0 = time.monotonic()
    totals = sched.run_until_drained(max_batch=chunk, pipeline=pipeline,
                                     overlap=overlap)
    elapsed = time.monotonic() - t0
    return totals, elapsed, sched


def _build_extender(n_nodes: int):
    """Sidecar backend + HTTP server over a hollow cluster, warmed so the
    first measured request never pays snapshot build + kernel compile."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )

    backend = TPUExtenderBackend()
    nodes = hollow_nodes(n_nodes)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 16}"
    backend.sync_nodes(nodes)
    backend.filter(make_pod("warm", cpu=100, memory=256 << 20), None, None)
    backend.prioritize(make_pod("warm2", cpu=100, memory=256 << 20),
                       None, None)
    srv = ExtenderHTTPServer(backend, prefix="/scheduler")
    srv.start()
    return backend, srv


def measure_compat_scheduleone(n_nodes: int, n_pods: int = 2000,
                               drivers: int = 8,
                               sync_interval_s: float = 1.0):
    """Compat-mode throughput: simulated scheduleOne loops driving the
    sidecar over REAL HTTP with the reference extender protocol
    (core/extender.go:100 Filter, :157 Prioritize, :199 Bind; wire structs
    api/types.go:158-204). Each driver is one scheduler's serial
    scheduleOne: POST /filter with the full candidate NodeNames list
    (nodeCacheCapable, extender.go:113-124), POST /prioritize with the
    survivors, pick the top score, POST /bind — so every bind is visible
    to every later evaluation, like a fleet of schedulers sharing one
    sidecar.

    Capacity feedback: the /bind wire carries only identifiers, so (as in
    the real deployment) the sidecar learns bound pods' RESOURCES from the
    periodic bulk cache sync — a housekeeping thread POSTs the full bound
    set to /cache/pods every `sync_interval_s` (the nodeCacheCapable
    snapshot-POST loop), so requested capacity accrues and scores move
    with load, and the measurement pays the re-sync invalidation cost too.
    Returns (pods_per_s, p50_ms, p99_ms, bound, unschedulable)."""
    import dataclasses
    import http.client
    import threading
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod

    backend, srv = _build_extender(n_nodes)
    node_names = list(backend.engine.snapshot.node_names)
    # the candidate list is invariant across the stream — serialize it once
    # per driver instead of per request (the scheduler equivalent: the
    # marshaled node-name set it would cache alongside its snapshot)
    names_json = json.dumps(node_names, separators=(",", ":"))
    lat_all = []
    bound = [0]
    unsched = [0]
    errors = []
    lock = threading.Lock()
    bound_specs = {}  # pod key -> encoded bound pod (for the bulk sync)
    done = threading.Event()
    per = (n_pods + drivers - 1) // drivers

    def syncer():
        # a dead syncer must FAIL the measurement like a dead driver does
        # (capacity feedback silently stopping would leave compat_pods_s
        # looking valid while no longer measuring what it claims); one
        # reconnect per failure, two consecutive failures abort
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        failures = 0
        while not done.wait(sync_interval_s):
            with lock:
                items = list(bound_specs.values())
            if not items:
                continue
            try:
                body = json.dumps({"items": items}, separators=(",", ":"))
                conn.request("POST", "/scheduler/cache/pods", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"cache sync HTTP {resp.status}")
                failures = 0
            except Exception as e:
                failures += 1
                try:
                    conn.close()
                except Exception:
                    pass
                if failures >= 2:
                    with lock:
                        errors.append(
                            f"syncer: {type(e).__name__}: {e}")
                    return
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=30)
        conn.close()

    def drive(d: int):
        try:
            _drive(d)
        except Exception as e:  # surface to the caller — a dead driver
            # thread must fail the measurement, not silently shrink it
            with lock:
                errors.append(f"driver {d}: {type(e).__name__}: {e}")

    def _drive(d: int):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def post_raw(path, body):
            conn.request("POST", f"/scheduler/{path}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = json.loads(resp.read())
            if resp.status != 200:  # explicit: bare assert vanishes
                # under python -O, silently corrupting the measurement
                raise RuntimeError(f"HTTP {resp.status} on {path}: {data}")
            return data

        lat = []
        n_bound = 0
        n_unsched = 0
        for i in range(per):
            if d * per + i >= n_pods:
                break
            pod = make_pod(f"compat-{d}-{i}", cpu=100, memory=256 << 20)
            enc = json.dumps(serde.encode_pod(pod), separators=(",", ":"))
            t0 = _time.perf_counter()
            out = post_raw(
                "filter",
                '{"Pod":' + enc + ',"NodeNames":' + names_json
                + ',"Nodes":null}')
            passed = out.get("NodeNames") or []
            if not passed:
                # counted, not silently dropped: an under-capacity run must
                # be visible in the result, like every other shrink path
                n_unsched += 1
                lat.append(_time.perf_counter() - t0)
                continue
            passed_json = names_json if len(passed) == len(node_names) \
                else json.dumps(passed, separators=(",", ":"))
            scores = post_raw(
                "prioritize",
                '{"Pod":' + enc + ',"NodeNames":' + passed_json
                + ',"Nodes":null}')
            host = max(scores, key=lambda e: e["Score"])["Host"]
            out = post_raw("bind", json.dumps(
                {"PodName": pod.name, "PodNamespace": pod.namespace,
                 "PodUID": pod.uid, "Node": host},
                separators=(",", ":")))
            if not out.get("Error"):
                n_bound += 1
                spec = serde.encode_pod(
                    dataclasses.replace(pod, node_name=host))
                with lock:
                    bound_specs[pod.key()] = spec
            lat.append(_time.perf_counter() - t0)
        conn.close()
        with lock:
            lat_all.extend(lat)
            bound[0] += n_bound
            unsched[0] += n_unsched

    threads = [threading.Thread(target=drive, args=(d,))
               for d in range(drivers)]
    sync_thread = None
    if sync_interval_s > 0:
        sync_thread = threading.Thread(target=syncer, daemon=True)
        sync_thread.start()
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    done.set()
    if sync_thread is not None:
        sync_thread.join(timeout=30)
    srv.stop()
    if errors:
        raise RuntimeError("; ".join(errors))
    lat_all.sort()
    if not lat_all or elapsed <= 0:
        return 0.0, None, None, 0, unsched[0]
    return (bound[0] / elapsed,
            lat_all[len(lat_all) // 2] * 1e3,
            lat_all[min(int(len(lat_all) * 0.99), len(lat_all) - 1)] * 1e3,
            bound[0], unsched[0])


def measure_wire_floor(n_clients: int = 100, per_client: int = 10,
                       bin_per_client: int = 50):
    """The ISSUE 11 wire-wall calibration, extracted from PROFILE_r12
    into a reproducible micro-scenario: measure the NO-OP transport on
    the CURRENT box — a ThreadingHTTPServer with an empty handler vs the
    async binary event loop answering PING — under ``n_clients``
    concurrent in-process client threads (the exact harness shape of the
    fleet benches). Both floors travel in the bench JSON so every fleet
    number ships with its platform wall attribution: an HTTP fleet
    reading at ~its floor is transport-saturated, not engine-saturated.

    Returns {"clients", "threaded_http_rps", "threaded_http_p50_ms",
    "threaded_http_p99_ms", "async_binary_rps", "async_binary_p50_ms",
    "async_binary_p99_ms", "binary_vs_http_floor"}."""
    import http.client
    import threading
    import time as _time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kubernetes_tpu.client.binarywire import BinaryWireClient
    from kubernetes_tpu.server.asyncwire import AsyncBinaryServer

    def run_clients(n, per, step):
        lat, errors = [], []
        lock = threading.Lock()
        start = threading.Barrier(n)

        def drive(c):
            try:
                start.wait(timeout=30)
                mine = []
                for _ in range(per):
                    t0 = _time.perf_counter()
                    step(c)
                    mine.append(_time.perf_counter() - t0)
                with lock:
                    lat.extend(mine)
            except Exception as e:  # a dead client shrinks the floor —
                # surface it instead of under-reporting the wall
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=drive, args=(c,))
                   for c in range(n)]
        t0 = _time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        elapsed = _time.monotonic() - t0
        if errors:
            raise RuntimeError("; ".join(errors[:3]))
        lat.sort()
        return (len(lat) / elapsed if elapsed > 0 else 0.0,
                lat[len(lat) // 2] * 1e3 if lat else None,
                lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3
                if lat else None)

    # ---- threaded HTTP no-op (the r12 harness, verbatim shape) ----------
    class _NoopHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            if length:
                self.rfile.read(length)
            body = b"{}"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _NoopThreaded(ThreadingHTTPServer):
        request_queue_size = 256
        daemon_threads = True

    httpd = _NoopThreaded(("127.0.0.1", 0), _NoopHandler)
    http_port = httpd.server_address[1]
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    conns = {}

    def http_step(c):
        conn = conns.get(c)
        if conn is None:
            conn = conns[c] = http.client.HTTPConnection(
                "127.0.0.1", http_port, timeout=120)
        conn.request("POST", "/noop", b"{}",
                     {"Content-Type": "application/json"})
        conn.getresponse().read()

    try:
        http_rps, http_p50, http_p99 = run_clients(
            n_clients, per_client, http_step)
    finally:
        for conn in conns.values():
            try:
                conn.close()
            except Exception:
                pass
        httpd.shutdown()
        http_thread.join(timeout=10)

    # ---- async binary no-op (PING never touches the service) ------------
    class _NoService:
        backend = None

    srv = AsyncBinaryServer(_NoService())
    srv.start()
    clients = {}

    def bin_step(c):
        cli = clients.get(c)
        if cli is None:
            cli = clients[c] = BinaryWireClient(
                "127.0.0.1", srv.port, timeout=120).connect()
        cli.ping()

    try:
        bin_rps, bin_p50, bin_p99 = run_clients(
            n_clients, bin_per_client, bin_step)
    finally:
        for cli in clients.values():
            cli.close()
        srv.stop()
    return {
        "clients": n_clients,
        "threaded_http_rps": round(http_rps, 1),
        "threaded_http_p50_ms": round(http_p50, 3) if http_p50 else None,
        "threaded_http_p99_ms": round(http_p99, 3) if http_p99 else None,
        "async_binary_rps": round(bin_rps, 1),
        "async_binary_p50_ms": round(bin_p50, 3) if bin_p50 else None,
        "async_binary_p99_ms": round(bin_p99, 3) if bin_p99 else None,
        "binary_vs_http_floor": round(bin_rps / http_rps, 2)
        if http_rps else None,
    }


def measure_multi_frontend(n_nodes: int, clients_list=(1, 10, 100),
                           pods_per_client: int = 0,
                           stale_window_ms: float = 25.0,
                           bind_fail_rate: float = 0.02,
                           bind_timeout_rate: float = 0.02,
                           tight_nodes: int = 64):
    """The ISSUE 9 headline: N concurrent compat scheduleOne loops against
    ONE extender sidecar over real HTTP — the multi-frontend service the
    ROADMAP targets (>=100 clients, >=100x the 19 pods/s r09 baseline).

    Each client is one scheduler's serial scheduleOne on a keep-alive
    connection, using the multi-frontend wire extensions: compact /filter
    (no 5k-name echo when everything passes), TopK /prioritize (ship the
    contenders, not the census — §Sparrow), and /bind carrying
    SnapshotGen + IdempotencyKey + the pod spec (exact fence math).
    Verdicts serve Omega-style from a bounded-staleness snapshot
    (stale_window_ms); every commit re-validates through the bind fence,
    CONFLICTs retry with jittered backoff, 429s honor Retry-After.

    Binds go through a REAL ApiServerLite store wrapped in FaultyBindApi
    (injected failures AND landed-timeouts), so the returned numbers carry
    a store-truth exactly-once audit: ``duplicate_binds`` counts pods the
    event log ever saw bound to two nodes — the hard-zero of the
    acceptance bar.

    Returns {"clients_<n>": {...}} per client count plus a capacity-tight
    run (``tight_nodes``) where the fence has something to refuse, so the
    conflict path is exercised, not just available."""
    import dataclasses
    import http.client
    import random as _random
    import re as _re
    import threading
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )
    from kubernetes_tpu.testing.churn import (
        FaultyBindApi,
        extender_store_binder,
    )

    def audit_duplicate_binds(api, prefix: str) -> int:
        """STORE-TRUTH exactly-once audit over the full event log: a pod
        whose MODIFIED events ever name two different nodes was double-
        booked. One implementation for every fleet — this is the hard-zero
        acceptance bar, and a weaker copy in one driver would silently
        weaken the claim."""
        first_node, dups = {}, 0
        for e in api._log:
            if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                    and e.obj.name.startswith(prefix):
                prev = first_node.setdefault(e.obj.name, e.obj.node_name)
                if prev != e.obj.node_name:
                    dups += 1
        return dups

    def run_fleet(n_clients: int, nn: int, per: int, label: str):
        api = ApiServerLite(max_log=max(200_000, 4 * (nn + n_clients * per)))
        nodes = hollow_nodes(nn)
        for i, n in enumerate(nodes):
            n.labels["zone"] = f"z{i % 16}"
        for n in nodes:
            api.create("Node", n)
        faulty = FaultyBindApi(api, fail_rate=bind_fail_rate,
                               timeout_rate=bind_timeout_rate, seed=nn)
        backend = TPUExtenderBackend(
            binder=extender_store_binder(faulty),
            stale_window_s=stale_window_ms / 1e3,
            coalesce_window_s=0.0005)
        backend.sync_nodes(nodes)
        backend.filter(make_pod("warm", cpu=100, memory=256 << 20),
                       None, None)
        # in-flight cap WELL below the client count: past it the server
        # sheds 429 + Retry-After instead of queueing requests into
        # multi-second tails — overload stays visible (shed_rate), tails
        # stay bounded
        srv = ExtenderHTTPServer(backend, prefix="/scheduler",
                                 max_inflight=min(max(n_clients, 16), 64))
        srv.start()
        specs = {}
        for c in range(n_clients):
            for i in range(per):
                p = make_pod(f"mf-{label}-{c}-{i}", cpu=100,
                             memory=256 << 20)
                api.create("Pod", p)
                specs[(c, i)] = p
        lat_all, errors = [], []
        conflicts = [0]
        retries = [0]
        shed429 = [0]
        bound_ct = [0]
        lock = threading.Lock()
        done = threading.Event()
        bound_specs = {}

        def syncer():
            # the nodeCacheCapable confirm loop (capacity feedback +
            # re-sync invalidation cost), as in compat mode; 2s cadence —
            # each sync clears the verdict memo fleet-wide, so at 100
            # clients the confirm freshness trades directly against tails
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=120)
            while not done.wait(2.0):
                with lock:
                    items = list(bound_specs.values())
                if not items:
                    continue
                try:
                    body = json.dumps({"items": items},
                                      separators=(",", ":"))
                    conn.request("POST", "/scheduler/cache/pods", body,
                                 {"Content-Type": "application/json"})
                    conn.getresponse().read()
                except Exception:
                    try:
                        conn.close()
                    except Exception:
                        pass
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", srv.port, timeout=120)
            conn.close()

        def drive(c: int):
            rng = _random.Random(77_000 + c)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=60)
            lat = []
            n_conf = n_retry = n_shed = n_bound = 0

            def post(path, obj):
                # reconnect-and-retry on socket timeouts / resets: SAFE BY
                # DESIGN — filter/prioritize are idempotent reads and bind
                # carries an IdempotencyKey, so a re-POST of the same body
                # is exactly the ledger's replay path (the at-most-once
                # ambiguity the service exists to absorb). This is what a
                # real frontend's HTTP client does.
                nonlocal conn
                body = json.dumps(obj, separators=(",", ":"))
                last = None
                for _try in range(3):
                    t0 = _time.perf_counter()
                    try:
                        conn.request("POST", f"/scheduler/{path}", body,
                                     {"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        data = json.loads(resp.read())
                        lat.append(_time.perf_counter() - t0)
                        return resp.status, data
                    except (TimeoutError, ConnectionError, OSError,
                            http.client.HTTPException) as e:
                        last = e
                        try:
                            conn.close()
                        except Exception:
                            pass
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", srv.port, timeout=60)
                raise RuntimeError(
                    f"{path}: {type(last).__name__}: {last}")

            def post_adm(path, obj):
                # admission-aware post: a 429 throttles THIS step with the
                # server's jittered backoff and retries it — backpressure
                # slows scheduleOne down, it doesn't fail it (a fresh
                # attempt would burn the retry budget on overload alone)
                nonlocal n_shed
                while True:
                    st, out = post(path, obj)
                    if st != 429:
                        return st, out
                    n_shed += 1
                    done.wait(out.get("RetryAfterMs", 20) / 1e3
                              * rng.uniform(0.5, 1.5))

            try:
                for i in range(per):
                    spec = specs[(c, i)]
                    enc = serde.encode_pod(spec)
                    bound = False
                    for attempt in range(80):
                        # fused verbs: ONE round trip answers filter AND
                        # the top-k scores of the same coalesced verdict
                        st, out = post_adm("filter", {
                            "Pod": enc, "NodeNames": None, "Nodes": None,
                            "Compact": True, "TopK": 32,
                            "DeadlineMs": 10_000})
                        if st == 504:
                            # deadline shed: by contract NOTHING happened
                            # — a fresh attempt is the retry, not a fleet
                            # failure (a loaded box queues past 10s)
                            n_shed += 1
                            done.wait(0.02 * rng.uniform(0.5, 1.5))
                            continue
                        if st != 200:
                            raise RuntimeError(f"filter HTTP {st}: {out}")
                        gen = out.get("SnapshotGen")
                        scores = out.get("TopScores")
                        if scores is None:
                            # legacy two-trip fallback (no fused support)
                            if out.get("AllPassed"):
                                cand = None
                            else:
                                cand = out.get("NodeNames") or []
                            st, scores = post_adm("prioritize", {
                                "Pod": enc, "NodeNames": cand,
                                "Nodes": None, "TopK": 32,
                                "DeadlineMs": 10_000})
                            if st != 200:
                                raise RuntimeError(
                                    f"prioritize HTTP {st}: {scores}")
                        if not scores:
                            # transiently full PER THE STALE VERDICT (the
                            # tight fleet's endgame): in-flight forgets /
                            # expiries free slots — retry, don't abort
                            n_retry += 1
                            done.wait(0.01 * rng.uniform(0.5, 1.5))
                            continue
                        best = max(e["Score"] for e in scores)
                        top = [e["Host"] for e in scores
                               if e["Score"] == best]
                        node = top[rng.randrange(len(top))]
                        st, out = post_adm("bind", {
                            "PodName": spec.name,
                            "PodNamespace": spec.namespace,
                            "PodUID": spec.uid, "Node": node,
                            "SnapshotGen": gen,
                            "IdempotencyKey": f"{spec.name}:{attempt}",
                            "Pod": enc, "DeadlineMs": 10_000})
                        err = out.get("Error", "")
                        if st == 409:
                            n_conf += 1
                            n_retry += 1
                            done.wait(out.get("RetryAfterMs", 5) / 1e3
                                      * rng.uniform(0.5, 1.5))
                            continue
                        if st == 200 and not err:
                            bound = True
                        elif "already assigned" in err:
                            bound = True  # landed earlier; store is truth
                            # ...and the store names WHERE — record that
                            # node, not the one this attempt raced for
                            m = _re.search(
                                r"already assigned to node (\S+)", err)
                            if m:
                                node = m.group(1)
                        else:
                            # ambiguous bind error: replay the SAME key —
                            # the ledger converges it to exactly-once
                            n_retry += 1
                            st2, out2 = post_adm("bind", {
                                "PodName": spec.name,
                                "PodNamespace": spec.namespace,
                                "PodUID": spec.uid, "Node": node,
                                "SnapshotGen": None,
                                "IdempotencyKey": f"{spec.name}:{attempt}",
                                "Pod": enc})
                            err2 = out2.get("Error", "")
                            if (st2 == 200 and not err2) \
                                    or "already assigned" in err2:
                                bound = True
                                m = _re.search(
                                    r"already assigned to node (\S+)",
                                    err2)
                                if m:
                                    node = m.group(1)
                            elif st2 == 409:
                                n_conf += 1
                                continue
                            else:
                                continue  # fresh attempt, fresh key
                        if bound:
                            n_bound += 1
                            full = serde.encode_pod(dataclasses.replace(
                                spec, node_name=node))
                            with lock:
                                bound_specs[spec.key()] = full
                            break
                    if not bound:
                        raise RuntimeError(f"{spec.name}: never bound")
            except Exception as e:
                with lock:
                    errors.append(f"client {c}: {type(e).__name__}: {e}")
            finally:
                conn.close()
                with lock:
                    lat_all.extend(lat)
                    conflicts[0] += n_conf
                    retries[0] += n_retry
                    shed429[0] += n_shed
                    bound_ct[0] += n_bound

        sync_th = threading.Thread(target=syncer, daemon=True)
        sync_th.start()
        threads = [threading.Thread(target=drive, args=(c,))
                   for c in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        done.set()
        sync_th.join(timeout=30)
        srv.stop()
        if errors:
            raise RuntimeError("; ".join(errors[:5]))
        dups = audit_duplicate_binds(api, "mf-")
        pods_now, _rv = api.list("Pod")
        store_bound = sum(1 for p in pods_now
                          if p.name.startswith("mf-") and p.node_name)
        lat_all.sort()
        with backend._counters_lock:
            srv_counters = dict(backend._counters)
        attempts = bound_ct[0] + conflicts[0]
        out = {
            "clients": n_clients,
            "nodes": nn,
            "pods_s": round(bound_ct[0] / elapsed, 1) if elapsed else 0.0,
            "bound": bound_ct[0],
            "store_bound": store_bound,
            "duplicate_binds": dups,
            "conflicts": conflicts[0],
            "conflict_rate": round(conflicts[0] / attempts, 4)
            if attempts else 0.0,
            "retries": retries[0],
            "shed_429": shed429[0],
            "shed_rate": round(shed429[0] / max(len(lat_all), 1), 4),
            "p50_request_ms": round(
                lat_all[len(lat_all) // 2] * 1e3, 3) if lat_all else None,
            "p99_request_ms": round(
                lat_all[min(int(len(lat_all) * 0.99),
                            len(lat_all) - 1)] * 1e3, 3)
            if lat_all else None,
            "injected_bind_failures": faulty.injected_failures,
            "injected_bind_timeouts": faulty.injected_timeouts,
            "srv_coalesce_batches": srv_counters.get("coalesce_batches", 0),
            "srv_coalesce_requests": srv_counters.get(
                "coalesce_requests", 0),
            "srv_bind_conflicts": srv_counters.get("bind_conflicts", 0),
            "srv_bind_replays": srv_counters.get("bind_replays", 0),
            "srv_admission_shed": srv_counters.get("admission_shed", 0),
            "srv_deadline_shed": srv_counters.get("deadline_shed", 0),
        }
        if dups:
            raise RuntimeError(
                f"multi-frontend audit FAILED: {dups} duplicate binds")
        return out

    def run_fleet_binary(n_clients: int, nn: int, per: int, label: str):
        """The same fleet protocol over the ASYNC BINARY wire (ISSUE 11):
        one event loop owns every socket (server/asyncwire.py), frames
        are the length-prefixed binary codec (server/framing.py), and a
        fleet scheduleOne is TWO round trips — fused FILTER(+TopK) and a
        spec-carrying BIND with SnapshotGen + IdempotencyKey in the
        frame. Same store, same injected faults, same exactly-once
        audit: the transport A/B against run_fleet isolates the wire."""
        from kubernetes_tpu.client.binarywire import (
            BinaryWireClient,
            WireDeadline,
            WireOverloaded,
        )
        from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
        from kubernetes_tpu.server.embedded import VerdictService

        api = ApiServerLite(max_log=max(200_000, 4 * (nn + n_clients * per)))
        nodes = hollow_nodes(nn)
        for i, n in enumerate(nodes):
            n.labels["zone"] = f"z{i % 16}"
        for n in nodes:
            api.create("Node", n)
        faulty = FaultyBindApi(api, fail_rate=bind_fail_rate,
                               timeout_rate=bind_timeout_rate, seed=nn + 2)
        backend = TPUExtenderBackend(
            binder=extender_store_binder(faulty),
            stale_window_s=stale_window_ms / 1e3,
            coalesce_window_s=0.0005)
        backend.sync_nodes(nodes)
        backend.filter(make_pod("warm", cpu=100, memory=256 << 20),
                       None, None)
        srv = AsyncBinaryServer(
            VerdictService(backend),
            max_batch=128,
            max_pending=min(max(n_clients, 16), 256),
            max_inflight=min(max(n_clients, 16), 128),
            workers=2)
        srv.start()
        from kubernetes_tpu.server import framing as _framing
        specs = {}
        blobs = {}
        for c in range(n_clients):
            for i in range(per):
                p = make_pod(f"mb-{label}-{c}-{i}", cpu=100,
                             memory=256 << 20)
                api.create("Pod", p)
                specs[(c, i)] = p
                # spec blob encoded ONCE per pod, reused across attempts
                # and both verbs (the binary twin of the HTTP drivers'
                # serialize-the-candidate-list-once discipline)
                blobs[(c, i)] = _framing.encode_pod_blob(p)
        lat_all, errors = [], []
        conflicts = [0]
        retries = [0]
        shed_ct = [0]
        bound_ct = [0]
        lock = threading.Lock()
        done = threading.Event()
        bound_specs = {}

        def syncer():
            # the nodeCacheCapable confirm loop over the binary SYNC verb
            # (capacity feedback + re-sync invalidation cost, as in the
            # HTTP fleet)
            cli = BinaryWireClient("127.0.0.1", srv.port, timeout=120)
            while not done.wait(2.0):
                with lock:
                    items = list(bound_specs.values())
                if not items:
                    continue
                try:
                    cli.sync_pods(items)
                except Exception:
                    cli.close()
            cli.close()

        def drive(c: int):
            rng = _random.Random(66_000 + c)
            cli = BinaryWireClient("127.0.0.1", srv.port, timeout=60)
            lat = []
            n_conf = n_retry = n_shed = n_bound = 0

            def timed(fn):
                # reconnect-and-retry on socket faults: SAFE BY DESIGN —
                # filter is an idempotent read, bind is ledger-keyed, so
                # a re-send of the same frame is exactly the replay path
                # (the HTTP clients' discipline, on the binary wire)
                last = None
                for _try in range(3):
                    t0 = _time.perf_counter()
                    try:
                        out = fn()
                        lat.append(_time.perf_counter() - t0)
                        return out
                    except (WireOverloaded, WireDeadline):
                        lat.append(_time.perf_counter() - t0)
                        raise
                    except (TimeoutError, ConnectionError, OSError) as e:
                        last = e
                        cli.close()
                raise RuntimeError(f"{type(last).__name__}: {last}")

            try:
                for i in range(per):
                    spec = specs[(c, i)]
                    blob = blobs[(c, i)]
                    bound = False
                    for attempt in range(80):
                        try:
                            v = timed(lambda: cli.filter_fused(
                                spec, top_k=32, deadline_ms=10_000,
                                pod_blob=blob))
                        except WireOverloaded as e:
                            n_shed += 1
                            done.wait(e.retry_after_s
                                      * rng.uniform(0.5, 1.5))
                            continue
                        except WireDeadline:
                            n_shed += 1
                            done.wait(0.005 * rng.uniform(0.5, 1.5))
                            continue
                        scores = v.top_scores or []
                        if not scores:
                            n_retry += 1
                            done.wait(0.01 * rng.uniform(0.5, 1.5))
                            continue
                        best = scores[0][1]
                        top = [h for h, s in scores if s == best]
                        node = top[rng.randrange(len(top))]
                        try:
                            r = timed(lambda: cli.bind(
                                spec.name, spec.namespace, spec.uid, node,
                                snapshot_gen=v.snapshot_gen,
                                idem_key=f"{spec.name}:{attempt}",
                                deadline_ms=10_000, pod_blob=blob))
                        except WireOverloaded as e:
                            n_shed += 1
                            done.wait(e.retry_after_s
                                      * rng.uniform(0.5, 1.5))
                            continue
                        except WireDeadline:
                            n_shed += 1
                            continue
                        if r.ok:
                            bound = True
                        elif r.retryable:
                            n_conf += 1
                            n_retry += 1
                            done.wait(r.retry_after_s
                                      * rng.uniform(0.5, 1.5))
                            continue
                        elif "already assigned" in r.error:
                            bound = True  # landed earlier; store is truth
                            m = _re.search(
                                r"already assigned to node (\S+)", r.error)
                            if m:
                                node = m.group(1)
                        elif r.kind == "error":
                            # ambiguous: replay the SAME key — the ledger
                            # converges it to exactly-once
                            n_retry += 1
                            try:
                                r2 = timed(lambda: cli.bind(
                                    spec.name, spec.namespace, spec.uid,
                                    node,
                                    idem_key=f"{spec.name}:{attempt}",
                                    pod_blob=blob))
                            except (WireOverloaded, WireDeadline):
                                continue
                            if r2.ok or "already assigned" in r2.error:
                                bound = True
                                m = _re.search(
                                    r"already assigned to node (\S+)",
                                    r2.error)
                                if m:
                                    node = m.group(1)
                            else:
                                continue
                        else:
                            continue  # shed: fresh attempt, fresh key
                        if bound:
                            n_bound += 1
                            with lock:
                                bound_specs[spec.key()] = \
                                    dataclasses.replace(spec,
                                                        node_name=node)
                            break
                    if not bound:
                        raise RuntimeError(f"{spec.name}: never bound")
            except Exception as e:
                with lock:
                    errors.append(f"client {c}: {type(e).__name__}: {e}")
            finally:
                cli.close()
                with lock:
                    lat_all.extend(lat)
                    conflicts[0] += n_conf
                    retries[0] += n_retry
                    shed_ct[0] += n_shed
                    bound_ct[0] += n_bound

        sync_th = threading.Thread(target=syncer, daemon=True)
        sync_th.start()
        threads = [threading.Thread(target=drive, args=(c,))
                   for c in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        done.set()
        sync_th.join(timeout=30)
        srv.stop()
        if errors:
            raise RuntimeError("; ".join(errors[:5]))
        dups = audit_duplicate_binds(api, "mb-")
        pods_now, _rv = api.list("Pod")
        store_bound = sum(1 for p in pods_now
                          if p.name.startswith("mb-") and p.node_name)
        lat_all.sort()
        with backend._counters_lock:
            srv_counters = dict(backend._counters)
        attempts = bound_ct[0] + conflicts[0]
        out = {
            "clients": n_clients,
            "nodes": nn,
            "transport": "async-binary",
            "pods_s": round(bound_ct[0] / elapsed, 1) if elapsed else 0.0,
            "bound": bound_ct[0],
            "store_bound": store_bound,
            "duplicate_binds": dups,
            "conflicts": conflicts[0],
            "conflict_rate": round(conflicts[0] / attempts, 4)
            if attempts else 0.0,
            "retries": retries[0],
            "shed_overload": shed_ct[0],
            "shed_rate": round(shed_ct[0] / max(len(lat_all), 1), 4),
            "p50_request_ms": round(
                lat_all[len(lat_all) // 2] * 1e3, 3) if lat_all else None,
            "p99_request_ms": round(
                lat_all[min(int(len(lat_all) * 0.99),
                            len(lat_all) - 1)] * 1e3, 3)
            if lat_all else None,
            "injected_bind_failures": faulty.injected_failures,
            "injected_bind_timeouts": faulty.injected_timeouts,
            "srv_wire_batches": srv_counters.get("wire_batches", 0),
            "srv_wire_requests": srv_counters.get("wire_requests", 0),
            "srv_bind_conflicts": srv_counters.get("bind_conflicts", 0),
            "srv_bind_replays": srv_counters.get("bind_replays", 0),
            "srv_admission_shed": srv_counters.get("admission_shed", 0),
            "srv_deadline_shed": srv_counters.get("deadline_shed", 0),
        }
        if dups:
            raise RuntimeError(
                f"binary-wire fleet audit FAILED: {dups} duplicate binds")
        return out

    def run_fleet_embedded(n_clients: int, nn: int, per: int, label: str):
        """The TRUE in-process embedding mode (server/embedded.py): N
        frontend threads link the verdict API as a library and drive
        EmbeddedVerdictAPI.schedule_one — coalescer/stale-window/fence/
        ledger intact, zero wire. Store-audited like every fleet."""
        from kubernetes_tpu.server.embedded import EmbeddedVerdictAPI

        api = ApiServerLite(max_log=max(200_000, 4 * (nn + n_clients * per)))
        nodes = hollow_nodes(nn)
        for i, n in enumerate(nodes):
            n.labels["zone"] = f"z{i % 16}"
        for n in nodes:
            api.create("Node", n)
        faulty = FaultyBindApi(api, fail_rate=bind_fail_rate,
                               timeout_rate=bind_timeout_rate, seed=nn + 3)
        emb = EmbeddedVerdictAPI(
            binder=extender_store_binder(faulty),
            stale_window_s=stale_window_ms / 1e3,
            coalesce_window_s=0.0005)
        emb.sync_nodes(nodes)
        emb.filter(make_pod("warm", cpu=100, memory=256 << 20))
        specs = {}
        for c in range(n_clients):
            for i in range(per):
                p = make_pod(f"me-{label}-{c}-{i}", cpu=100,
                             memory=256 << 20)
                api.create("Pod", p)
                specs[(c, i)] = p
        errors, lock = [], threading.Lock()
        bound_ct = [0]
        attempts_ct = [0]

        def drive(c: int):
            rng = _random.Random(99_000 + c)
            n_bound = n_att = 0
            try:
                for i in range(per):
                    _node, att = emb.schedule_one(specs[(c, i)], top_k=32,
                                                  rng=rng)
                    n_bound += 1
                    n_att += att
            except Exception as e:
                with lock:
                    errors.append(f"client {c}: {type(e).__name__}: {e}")
            finally:
                with lock:
                    bound_ct[0] += n_bound
                    attempts_ct[0] += n_att

        threads = [threading.Thread(target=drive, args=(c,))
                   for c in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        if errors:
            raise RuntimeError("; ".join(errors[:5]))
        dups = audit_duplicate_binds(api, "me-")
        if dups:
            raise RuntimeError(
                f"embedded fleet audit FAILED: {dups} duplicate binds")
        with emb.backend._counters_lock:
            srv_counters = dict(emb.backend._counters)
        return {
            "clients": n_clients,
            "nodes": nn,
            "transport": "embedded",
            "pods_s": round(bound_ct[0] / elapsed, 1) if elapsed else 0.0,
            "bound": bound_ct[0],
            "duplicate_binds": dups,
            "attempts_per_bind": round(attempts_ct[0]
                                       / max(bound_ct[0], 1), 3),
            "injected_bind_failures": faulty.injected_failures,
            "injected_bind_timeouts": faulty.injected_timeouts,
            "srv_coalesce_batches": srv_counters.get("coalesce_batches", 0),
            "srv_bind_conflicts": srv_counters.get("bind_conflicts", 0),
            "srv_bind_replays": srv_counters.get("bind_replays", 0),
        }

    def run_fleet_inproc(n_clients: int, nn: int, per: int, label: str):
        """The same fleet protocol WITHOUT the HTTP socket layer: 100
        logical frontends as threads against the backend's verdict API
        directly. This measures the SERVICE's multi-client capacity —
        coalescer, stale-window memo, fence, ledger, lock discipline,
        injected store faults, store-truth audit — separated from the
        Python http.server platform ceiling (a no-op ThreadingHTTPServer
        with 100 in-process clients measures ~200 req/s on the 2-core CI
        box; the wire fleet above reports against THAT ceiling, this one
        reports what the service itself sustains)."""
        from kubernetes_tpu.server.coalescer import (
            DeadlineExceeded as _Dl,
            Overloaded as _Ovl,
        )
        api = ApiServerLite(max_log=max(200_000, 4 * (nn + n_clients * per)))
        nodes = hollow_nodes(nn)
        for i, n in enumerate(nodes):
            n.labels["zone"] = f"z{i % 16}"
        for n in nodes:
            api.create("Node", n)
        faulty = FaultyBindApi(api, fail_rate=bind_fail_rate,
                               timeout_rate=bind_timeout_rate, seed=nn + 1)
        backend = TPUExtenderBackend(
            binder=extender_store_binder(faulty),
            stale_window_s=stale_window_ms / 1e3,
            coalesce_window_s=0.0005)
        backend.sync_nodes(nodes)
        backend.filter(make_pod("warm", cpu=100, memory=256 << 20),
                       None, None)
        specs = {}
        for c in range(n_clients):
            for i in range(per):
                p = make_pod(f"mfi-{label}-{c}-{i}", cpu=100,
                             memory=256 << 20)
                api.create("Pod", p)
                specs[(c, i)] = p
        lock = threading.Lock()
        errors, lat_all = [], []
        conflicts = [0]
        retries = [0]
        sheds = [0]
        bound_ct = [0]

        def drive(c: int):
            rng = _random.Random(88_000 + c)
            lat = []
            n_conf = n_retry = n_shed = n_bound = 0
            try:
                for i in range(per):
                    spec = specs[(c, i)]
                    bound = False
                    for attempt in range(80):
                        t0 = _time.perf_counter()
                        try:
                            # fused verbs: one window ticket answers both
                            _p, _f, scores, gen = backend.fused_verdict(
                                spec, None, deadline_s=10.0, top_k=32)
                        except _Ovl as e:
                            n_shed += 1
                            _time.sleep(e.retry_after_s
                                        * rng.uniform(0.5, 1.5))
                            continue
                        except _Dl:
                            n_shed += 1
                            _time.sleep(0.005 * rng.uniform(0.5, 1.5))
                            continue
                        if not scores:
                            n_retry += 1
                            _time.sleep(0.01 * rng.uniform(0.5, 1.5))
                            continue
                        best = scores[0][1]
                        cands = [nm for nm, s in scores if s == best]
                        node = cands[rng.randrange(len(cands))]
                        err, kind, retry_s = backend.bind_verdict(
                            spec.name, spec.namespace, spec.uid, node,
                            snapshot_gen=gen,
                            idem_key=f"{spec.name}:{attempt}",
                            pod_spec=spec)
                        lat.append(_time.perf_counter() - t0)
                        if kind == "ok":
                            bound = True
                        elif kind in ("conflict", "pending"):
                            n_conf += 1
                            n_retry += 1
                            _time.sleep(retry_s * rng.uniform(0.5, 1.5))
                            continue
                        elif "already assigned" in err:
                            bound = True
                        else:
                            n_retry += 1
                            err2, kind2, _r = backend.bind_verdict(
                                spec.name, spec.namespace, spec.uid, node,
                                snapshot_gen=None,
                                idem_key=f"{spec.name}:{attempt}",
                                pod_spec=spec)
                            if kind2 == "ok" or "already assigned" in err2:
                                bound = True
                            else:
                                continue
                        if bound:
                            n_bound += 1
                            break
                    if not bound:
                        raise RuntimeError(f"{spec.name} never bound")
            except Exception as e:
                with lock:
                    errors.append(f"client {c}: {type(e).__name__}: {e}")
            finally:
                with lock:
                    lat_all.extend(lat)
                    conflicts[0] += n_conf
                    retries[0] += n_retry
                    sheds[0] += n_shed
                    bound_ct[0] += n_bound

        threads = [threading.Thread(target=drive, args=(c,))
                   for c in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        if errors:
            raise RuntimeError("; ".join(errors[:5]))
        dups = audit_duplicate_binds(api, "mfi-")
        if dups:
            raise RuntimeError(
                f"in-proc fleet audit FAILED: {dups} duplicate binds")
        lat_all.sort()
        with backend._counters_lock:
            srv_counters = dict(backend._counters)
        attempts = bound_ct[0] + conflicts[0]
        return {
            "clients": n_clients,
            "nodes": nn,
            "pods_s": round(bound_ct[0] / elapsed, 1) if elapsed else 0.0,
            "bound": bound_ct[0],
            "duplicate_binds": dups,
            "conflicts": conflicts[0],
            "conflict_rate": round(conflicts[0] / attempts, 4)
            if attempts else 0.0,
            "retries": retries[0],
            "shed_overload": sheds[0],
            "p99_scheduleone_ms": round(
                lat_all[min(int(len(lat_all) * 0.99),
                            len(lat_all) - 1)] * 1e3, 3)
            if lat_all else None,
            "injected_bind_failures": faulty.injected_failures,
            "injected_bind_timeouts": faulty.injected_timeouts,
            "srv_coalesce_batches": srv_counters.get("coalesce_batches", 0),
            "srv_coalesce_requests": srv_counters.get(
                "coalesce_requests", 0),
            "srv_bind_conflicts": srv_counters.get("bind_conflicts", 0),
            "srv_bind_replays": srv_counters.get("bind_replays", 0),
        }

    def run_quiesced(fn, *a):
        """Collector quiescence for one fleet measurement (the same
        CPython service tuning the headline drain applies): a gen-2 GC
        pass over a heap holding several prior fleets' clusters reads as
        hundreds of ms of request latency charged to whichever transport
        happened to be under test — quiesce uniformly so the A/B
        compares transports, not collection timing."""
        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            return fn(*a)
        finally:
            gc.enable()
            gc.unfreeze()

    if not pods_per_client:
        pods_per_client = int(os.environ.get("BENCH_MF_PODS_PER_CLIENT", 0))
    results = {}
    for n_clients in clients_list:
        per = pods_per_client or max(20, min(200, 2000 // n_clients))
        try:
            results[f"clients_{n_clients}"] = run_quiesced(
                run_fleet, n_clients, n_nodes, per, str(n_clients))
        except Exception as e:  # one fleet's failure must not hide the
            # others' numbers; the error travels in the artifact
            results[f"clients_{n_clients}"] = {
                "clients": n_clients, "error": f"{type(e).__name__}: {e}"}
    # transport A/B (ISSUE 11): the SAME fleets over the async binary
    # wire — one event loop, binary frames, two round trips per
    # scheduleOne — against the same store with the same injected faults
    # and the same hard-zero duplicate audit
    for n_clients in clients_list:
        per = pods_per_client or max(20, min(200, 2000 // n_clients))
        try:
            results[f"binwire_{n_clients}"] = run_quiesced(
                run_fleet_binary, n_clients, n_nodes, per,
                str(n_clients))
        except Exception as e:
            results[f"binwire_{n_clients}"] = {
                "clients": n_clients, "error": f"{type(e).__name__}: {e}"}
    # service-capacity fleet: the same 100-frontend protocol without the
    # Python http.server platform in the measurement loop
    big = max(clients_list)
    try:
        results["inproc"] = run_quiesced(
            run_fleet_inproc, big, n_nodes,
            pods_per_client or max(20, min(200, 20_000 // big)), "ip")
    except Exception as e:
        results["inproc"] = {"clients": big,
                             "error": f"{type(e).__name__}: {e}"}
    # the TRUE embedding mode (ISSUE 11): frontends LINK the verdict API
    # (EmbeddedVerdictAPI.schedule_one), coalescer/fence/ledger intact
    try:
        results["embedded"] = run_quiesced(
            run_fleet_embedded, big, n_nodes,
            pods_per_client or max(20, min(200, 20_000 // big)), "emb")
    except Exception as e:
        results["embedded"] = {"clients": big,
                               "error": f"{type(e).__name__}: {e}"}
    # capacity-tight fleet: few nodes filled to ~98% (hollow nodes take 40
    # of these 100m pods by CPU), so the endgame races the last slots
    # through stale verdicts and the fence genuinely refuses — the
    # conflict/retry contract measured under real contention, not just
    # available
    tight_clients = min(max(clients_list), 32)
    try:
        results["tight"] = run_quiesced(
            run_fleet, tight_clients, tight_nodes,
            max(8, int(tight_nodes * 40 * 0.98) // tight_clients), "tight")
    except Exception as e:
        results["tight"] = {"clients": tight_clients,
                            "error": f"{type(e).__name__}: {e}"}
    # ...and the tight endgame over the binary wire: the fence must
    # refuse (and heal) identically when the transport swaps
    try:
        results["binwire_tight"] = run_quiesced(
            run_fleet_binary, tight_clients, tight_nodes,
            max(8, int(tight_nodes * 40 * 0.98) // tight_clients),
            "tight")
    except Exception as e:
        results["binwire_tight"] = {"clients": tight_clients,
                                    "error": f"{type(e).__name__}: {e}"}
    return results


def measure_multiproc(n_nodes: int = 64, workers_list=(1, 2),
                      pods_per_worker: int = 96, overlaps=(0.5,),
                      relist_every: int = 16) -> dict:
    """Process-fleet scaling (ISSUE 16): M FULL scheduler processes —
    own interpreter, own evaluator, own bounded-stale snapshot — over
    one shared cell through the fenced binary wire (the paper's Omega
    shape, not the thread fleets' GIL-shared approximation).

    Two sweeps: (a) scheduleOnes/s vs process count on DISJOINT pending
    pools (multiproc_N keys — the scaling headline: M=2 should beat
    M=1 on a multi-core box because the decision path has no shared
    interpreter); (b) conflict rate vs pending-pool OVERLAP at max M
    (multiproc_N_overlapP keys — Omega's conflict economics: every
    contested pod costs W-1 typed double-claim refusals, and the store
    audit must stay at hard zero duplicates throughout)."""
    from kubernetes_tpu.parallel.multiproc import run_process_fleet

    def slim(agg: dict) -> dict:
        return {
            "workers": agg["workers"],
            "pods_per_worker": agg["pods_per_worker"],
            "overlap": agg["overlap"],
            "pods_s": round(agg["scheduled_pods_s"], 1),
            "binds": agg["binds"],
            "wall_s": round(agg["wall_s"], 3),
            "conflicts": agg["conflicts"],
            "conflict_rate": round(agg["conflict_rate"], 4),
            "double_claim": agg["double_claim"],
            "stale_snapshot": agg["stale_snapshot"],
            "relists": agg["relists"],
            "gave_up": agg["gave_up"],
            "server_bind_conflicts": agg["server_bind_conflicts"],
            "server_conflict_reasons": agg["server_conflict_reasons"],
            "duplicate_binds": agg["duplicate_binds"],
            "worker_failures": agg["worker_failures"],
            "missing_workers": agg["missing_workers"],
        }

    out: dict = {"cpus": os.cpu_count()}
    for m in workers_list:
        r = run_process_fleet(
            int(m), pods_per_worker=pods_per_worker, overlap=0.0,
            n_nodes=n_nodes, relist_every=relist_every,
            pod_prefix=f"mpb{m}", timeout_s=420.0)
        out[f"multiproc_{m}"] = slim(r["agg"])
    m_max = max(int(m) for m in workers_list)
    for ov in overlaps:
        ov = float(ov)
        if ov <= 0.0:
            continue
        r = run_process_fleet(
            m_max, pods_per_worker=pods_per_worker, overlap=ov,
            n_nodes=n_nodes, relist_every=relist_every,
            pod_prefix=f"mpbo{int(ov * 100)}", timeout_s=420.0)
        out[f"multiproc_{m_max}_overlap_{int(ov * 100)}"] = slim(r["agg"])
    one = out.get("multiproc_1", {}).get("pods_s")
    top = out.get(f"multiproc_{m_max}", {}).get("pods_s")
    if one and top:
        out["scaling_max_vs_1"] = round(top / one, 2)
    out["duplicate_binds_max"] = max(
        (v.get("duplicate_binds", 0) for k, v in out.items()
         if isinstance(v, dict) and k.startswith("multiproc_")),
        default=0)
    return out


def measure_federation(n_cells: int = 4, nodes_per_cell: int = 50_000,
                       n_pods: int = 1600, batch: int = 64,
                       rate: float = 0.0, brownout_down_s: float = 1.5,
                       boot_timeout_s: float = 420.0,
                       drain_timeout_s: float = 300.0) -> dict:
    """The ISSUE 20 acceptance scenario: M cell PROCESSES (each the r18
    engine unchanged behind server/asyncwire.py, its own store and
    always-on loop) behind ONE FederationRouter, admission scored over
    the fused [C, M] cell-aggregate tensor and committed over the binary
    wire with idempotency keys.

    Mid-offer a BrownoutDriver takes one cell NotReady: its pending pods
    evacuate through the spillover path to the survivors; after the
    offer, spill pumps drain every backlog to zero. The acceptance audit
    is store truth and HARD-FAILS the scenario: per-cell
    audit_duplicate_binds must be zero AND no pod key may appear bound
    in two different cells' final stores (one bound cell per pod, ever).

    Offered rate is auto-scaled to the box (rate=0 -> 250*cpus pods/s)
    and disclosed beside every number with the cpu count — a 1-core box
    runs M schedulers + the router on one core, so the absolute
    throughput reads against that shape, never against a fleet's."""
    import multiprocessing
    import statistics

    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.engine.gang import (
        GANG_MIN_AVAILABLE_ANNOTATION,
        GANG_NAME_ANNOTATION,
    )
    from kubernetes_tpu.federation.cell import run_cell_process
    from kubernetes_tpu.federation.router import FederationRouter, WireCell
    from kubernetes_tpu.testing.churn import (
        BrownoutDriver,
        make_brownout_schedule,
    )

    cpus = os.cpu_count() or 1
    if not rate:
        rate = 250.0 * cpus
    names = [f"cell{i}" for i in range(n_cells)]
    zones = 8
    ctx = multiprocessing.get_context("spawn")
    procs = []
    try:
        for i, name in enumerate(names):
            out_q = ctx.Queue()
            ctrl_q = ctx.Queue()
            cfg = {"cell": name, "n_nodes": nodes_per_cell, "seed": i,
                   "zones": zones, "spill_after_attempts": 2}
            p = ctx.Process(target=run_cell_process,
                            args=(cfg, out_q, ctrl_q),
                            name=f"fed-{name}", daemon=True)
            p.start()
            procs.append({"name": name, "proc": p, "out": out_q,
                          "ctrl": ctrl_q})
        # ---- boot barrier: every cell announces its ephemeral port
        t0 = time.monotonic()
        for rec in procs:
            left = boot_timeout_s - (time.monotonic() - t0)
            msg = rec["out"].get(timeout=max(left, 1.0))
            if not msg.get("ok"):
                raise RuntimeError(
                    f"cell {rec['name']} failed to boot: "
                    f"{msg.get('error')}")
            rec["port"] = msg["port"]
        boot_s = time.monotonic() - t0
        router = FederationRouter(
            [WireCell(r["name"], "127.0.0.1", r["port"]) for r in procs])
        th = time.monotonic()
        router.hydrate()
        hydrate_s = time.monotonic() - th
        agg_nodes = sum(a.nodes_total for a in router.aggs.values())

        # ---- warm the route+admit path (first batch pays np/jit import
        # + per-cell first-create; its span would report warm cost as
        # admission latency)
        warm = [make_pod(f"fedwarm-{i}", cpu=100, memory=64 * 1024 ** 2)
                for i in range(8)]
        router.admit(warm)
        router.admit_spans.clear()

        # ---- the offered stream: plain pods + zone-pinned pods (the
        # affinity-domain routing leg — each cell's zones are disjoint by
        # construction, so a zone selector admits to exactly one cell) +
        # whole-cell gangs
        pods: list = []
        for i in range(n_pods):
            if i % 8 == 5:
                cell_i = (i // 8) % n_cells
                sel = {"zone": f"{names[cell_i]}-z{i % zones}"}
                p = make_pod(f"fedp-{i}", cpu=100,
                             memory=64 * 1024 ** 2, node_selector=sel)
            else:
                p = make_pod(f"fedp-{i}", cpu=100,
                             memory=64 * 1024 ** 2)
            pods.append(p)
        n_gangs = 4
        for g in range(n_gangs):
            for m in range(6):
                p = make_pod(f"fedgang{g}-{m}", cpu=50,
                             memory=32 * 1024 ** 2)
                p.annotations[GANG_NAME_ANNOTATION] = f"fedgang{g}"
                p.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = "6"
                pods.append(p)
        offer_s = len(pods) / rate
        schedule = make_brownout_schedule(
            names, duration_s=max(offer_s, brownout_down_s * 2 + 1.0),
            down_s=brownout_down_s, count=1, seed=0)
        driver = BrownoutDriver(router, schedule)
        t_start = time.monotonic()
        sent = 0
        while sent < len(pods):
            now = time.monotonic() - t_start
            driver.apply_until(now)
            due = min(len(pods), int(now * rate) + batch)
            if due > sent:
                router.admit(pods[sent:due])
                sent = due
                if (sent // batch) % 4 == 0:
                    router.refresh()
            else:
                time.sleep(min(batch / rate, 0.05))
        offer_wall_s = time.monotonic() - t_start

        # ---- drain: spill pumps move every backlog/spill to a cell
        # that fits until global pending is zero (and the brownout
        # schedule has fully played out, recover included)
        td = time.monotonic()
        pending = -1
        while time.monotonic() - td < drain_timeout_s:
            driver.apply_until(time.monotonic() - t_start)
            router.spill_pump()
            pending = sum(a.pending for a in router.aggs.values())
            if pending == 0 and not router.backlog and driver.done():
                break
            time.sleep(0.1)
        drain_s = time.monotonic() - td
        counters = router.counters_snapshot()
        spans = sorted(d for _t, d, _n in router.admit_spans)
        p50 = statistics.median(spans) * 1e3 if spans else 0.0
        p99_ms = router.admission_p99_ms()
        # steady-batch p99: admission spans at the offered batch size
        # only. The all-batches p99 above includes the brownout
        # evacuation (one batch carrying EVERY pending pod of the dead
        # cell, admitted while the survivors chew on one core) — real
        # work, disclosed separately so the steady admission latency is
        # readable beside it
        steady = sorted(d for _t, d, n in router.admit_spans
                        if n <= batch)
        sp99 = 0.0
        if steady:
            i = min(len(steady) - 1,
                    int(round(0.99 * (len(steady) - 1))))
            sp99 = steady[i] * 1e3
        router.close()

        # ---- stop the fleet, collect STORE-truth finals
        for rec in procs:
            rec["ctrl"].put("stop")
        finals = {}
        for rec in procs:
            msg = rec["out"].get(timeout=60.0)
            while not msg.get("final"):
                msg = rec["out"].get(timeout=60.0)
            finals[rec["name"]] = msg
            rec["proc"].join(timeout=30.0)

        # ---- the acceptance audits (hard-fail: a federation number over
        # a double-bound pod is not a number)
        dup_per_cell = {}
        owner: dict = {}
        cross_cell = 0
        for name, f in finals.items():
            if not f.get("ok"):
                raise RuntimeError(
                    f"cell {name} died: {f.get('error')}")
            dup_per_cell[name] = f["duplicate_binds"]
            for key in f["bound"]:
                if key in owner and owner[key] != name:
                    cross_cell += 1
                owner[key] = name
        if cross_cell or any(dup_per_cell.values()):
            raise RuntimeError(
                f"federation exactly-once audit FAILED: cross-cell "
                f"double binds={cross_cell}, per-cell duplicates="
                f"{dup_per_cell}")
        bound_total = sum(len(f["bound"]) for f in finals.values())
        pending_final = sum(f["pending"] for f in finals.values())
        moved = counters["spill_moved"] + counters["evacuated_moved"]
        spillover_bound = max(moved - pending_final - len(router.backlog),
                              0)
        return {
            "cpus": cpus,
            "cells": n_cells,
            "nodes_per_cell": nodes_per_cell,
            "agg_nodes": agg_nodes,
            "zones_per_cell": zones,
            "boot_s": round(boot_s, 3),
            "hydrate_s": round(hydrate_s, 3),
            "offered_pods": len(pods) + len(warm),
            "offered_rate_pods_s": rate,
            "offer_wall_s": round(offer_wall_s, 3),
            "gangs": n_gangs,
            "admission_batch": batch,
            "router_admission_p50_ms": round(p50, 3),
            "router_admission_p99_ms": round(p99_ms, 3),
            "router_admission_steady_p99_ms": round(sp99, 3),
            "router_admission_batches": len(spans),
            "brownout": {"cell": schedule[0].cell,
                         "t": schedule[0].t,
                         "down_s": schedule[0].down_s},
            "evacuated_moved": counters["evacuated_moved"],
            "spill_moved": counters["spill_moved"],
            "spillover_bound": spillover_bound,
            "bound_total": bound_total,
            "pending_final": pending_final,
            "backlog_final": len(router.backlog),
            "drain_s": round(drain_s, 3),
            "drained_to_zero": bool(pending == 0),
            "duplicate_binds_per_cell": dup_per_cell,
            "cross_cell_double_binds": cross_cell,
            "router_counters": counters,
            "per_cell": {
                name: {"bound": len(f["bound"]),
                       "pending": f["pending"],
                       "counters": f["counters"]}
                for name, f in finals.items()},
        }
    finally:
        for rec in procs:
            if rec["proc"].is_alive():
                try:
                    rec["ctrl"].put("stop")
                except Exception:
                    pass
        for rec in procs:
            rec["proc"].join(timeout=10.0)
            if rec["proc"].is_alive():
                rec["proc"].terminate()


def _ab_ranges_overlap(a, b) -> bool:
    """True when two A/B arm trial distributions overlap — the r17
    escalation trigger (ISSUE 20 satellite): overlapping arm ranges
    cannot resolve a small overhead bar, so both on/off A/Bs escalate
    to more interleaved trials per arm until the ranges separate or
    the trial cap lands."""
    return bool(a) and bool(b) and min(a) <= max(b) \
        and min(b) <= max(a)


def _ratio(results, a: str, b: str):
    """pods_s ratio between two fleet results, None when either is
    missing/errored (the A/B must never invent a number)."""
    ra = (results.get(a) or {}).get("pods_s")
    rb = (results.get(b) or {}).get("pods_s")
    if not ra or not rb:
        return None
    return round(ra / rb, 2)


_STREAM_WARMED: set = set()


def _mesh_or_none(mesh_devices: int):
    """make_mesh(mesh_devices) when >1 forced host devices are available;
    the 1-device request is the unsharded engine by definition."""
    if not mesh_devices or int(mesh_devices) <= 1:
        return None
    from kubernetes_tpu.parallel.mesh import make_mesh
    return make_mesh(int(mesh_devices))


def _warm_stream_shapes(n_nodes: int, sizes, profile: str = "density",
                        mesh_devices: int = 0):
    """Compile the micro-wave shape ladder BEFORE a measured stream: one
    throwaway cluster, one fixed-chunk drain per ladder size, so the
    adaptive quantum's growth path never pays an XLA compile mid-offer
    (a multi-second stall that would be charged to create->bound and
    reported as scheduler latency — the exact confound the creator-burst
    satellite exists to kill on the arrival side). In-process jit caches
    are global, so the real run reuses these executables; the persistent
    compile cache makes repeat processes cheap too."""
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    todo = [s for s in sizes
            if (n_nodes, profile, s, mesh_devices) not in _STREAM_WARMED]
    if not todo:
        return
    api = ApiServerLite(max_log=max(200_000,
                                    3 * (n_nodes + sum(todo) + 1000)))
    load_cluster(api, hollow_nodes(n_nodes), [])
    sched = Scheduler(api, record_events=False,
                      mesh=_mesh_or_none(mesh_devices))
    sched.start()
    for sz in todo:
        for p in PROFILES[profile](sz):
            p.name = f"warm{sz}-{p.name}"
            api.create("Pod", p)
        sched.run_until_drained(max_batch=sz)
        _STREAM_WARMED.add((n_nodes, profile, sz, mesh_devices))


def measure_fastlane_mixed(n_nodes: int = 256, rate: float = 2000.0,
                           fast_rate: float = 100.0,
                           duration_s: float = 3.0,
                           budget_ms: float = 250.0,
                           probe_pods: int = 64) -> dict:
    """Mixed-criticality scenario (ISSUE 17): ONE warm always-on loop
    with the Sparrow fast lane armed, measured in three windows on the
    same box, same process, same resident state:

    - **solo**: the bulk stream alone at ``rate`` — the same-run
      baseline the mixed window's bulk rate reads against (a cross-run
      ratio would be box noise arbitrage on a ±30% machine);
    - **mixed**: the SAME bulk stream plus latency-critical pods at
      ``fast_rate``. Headlines: fast-tier p99 create->bound (the sub-
      10 ms acceptance bar) and ``mixed_bulk_sustained`` — the bulk
      tier's sustained rate as a fraction of its solo rate (>= 0.90:
      the fast tier must not starve the waves it threads between);
    - **probe**: ``probe_pods`` fast pods with NO bulk traffic, span
      counters diffed around the window — the delta-free proof (zero
      encoding builds, zero full snapshot walks per fast pod) as
      artifact numbers, not prose.

    Exactly-once is audited the run_arrival way (a pod key in two bind
    observer passes = a duplicate) PLUS store truth (every pod landed,
    exactly one node each); the fast lane's typed outcome counters
    (bound / fell_back / bind_error / superseded) travel alongside and
    must partition the fast pods created."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.engine.fastlane import FASTLANE_ANNOTATION
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.utils.trace import COUNTERS as _counters
    import numpy as np
    import threading

    budget_s = budget_ms / 1e3
    total_bulk = int(rate * duration_s)
    n_fast = int(fast_rate * duration_s)
    # pods accumulate across the three windows (nothing is deleted —
    # the lane must thread through a FULL cluster, not an emptying one)
    # and a hollow node CPU-binds at 40 density pods: size the cluster
    # so the last probe pod still has headroom, or the tail would hang
    # unschedulable until the deadline
    need = 2 * total_bulk + n_fast + probe_pods + 64
    n_nodes = max(n_nodes, -(-need // 36))
    interval_s = min(1.0, max(0.25, round(duration_s / 4.0, 2)))
    all_bulk = PROFILES["density"](2 * total_bulk)
    solo_pods, mixed_pods = all_bulk[:total_bulk], all_bulk[total_bulk:]

    def fast_pod(i: int):
        p = make_pod(f"fastbench-{i}", cpu=100, memory=128 << 20)
        p.annotations[FASTLANE_ANNOTATION] = "true"
        return p

    api = ApiServerLite(max_log=max(200_000, 6 * (n_nodes + total_bulk)))
    load_cluster(api, hollow_nodes(n_nodes), [])
    sched = Scheduler(api, record_events=False)
    sched.start()
    # cap the micro-wave quantum: the fast pump runs at step-top and in
    # the harvest-overlap poll, so the worst-case fast wait is one
    # wave's UNPUMPABLE host section (harvest fence + assume fold +
    # bind flush). At 2000/s the default ladder grows waves past 1k
    # pods whose host section alone is tens of ms on a 1-core box —
    # small waves keep every section under the 10 ms objective, and
    # both measured windows share the cap so the solo/mixed ratio is
    # apples to apples (128-pod waves still sustain several x the offer)
    loop = sched.stream(budget_s=budget_s, min_quantum=64,
                        max_quantum=128, fastlane=True)
    # prime: boot costs (first snapshot build, encoding, compiles) land
    # here, not in any measured window — including the WHOLE micro-wave
    # shape ladder (64/128/256). A first-use XLA compile inside a
    # measured window stalls the loop for hundreds of ms on a small
    # box, and that stall lands straight in the fast tier's p99 (the
    # bimodal-tail failure this prime pins down)
    for q in (64, 128):
        for p in PROFILES["density"](q):
            p.name = f"prime{q}-" + p.name
            api.create("Pod", p)
        sched.sync()
        loop.quantum = q
        loop.step()
    loop.quantum = 64
    loop.drain()

    bind_events = []                 # (t_abs, [keys]) across ALL windows
    sched.wave_observer = lambda ts, keys: bind_events.append((ts, keys))
    create_ts: dict = {}             # key -> create instant (abs)
    fast_keys: set = set()

    def offer_window(bulk, fasts):
        """Offer bulk at `rate` (+ fasts at `fast_rate`) and run the
        loop until settled; returns (t0, offer_end_abs)."""
        t0 = time.monotonic()

        def creator(pods_, rate_):
            made = 0
            while made < len(pods_):
                due = min(len(pods_),
                          int(rate_ * (time.monotonic() - t0)),
                          made + max(4, int(rate_ * 0.004)))
                if due > made:
                    for p in pods_[made:due]:
                        api.create("Pod", p)
                    ts = time.monotonic()
                    for p in pods_[made:due]:
                        create_ts[p.key()] = ts
                    made = due
                delay = t0 + (made + 1) / rate_ - time.monotonic()
                if delay > 0:
                    time.sleep(min(delay, 0.002))

        threads = []
        if bulk:
            threads.append(threading.Thread(
                target=creator, args=(bulk, rate), daemon=True))
        if fasts:
            threads.append(threading.Thread(
                target=creator, args=(fasts, fast_rate), daemon=True))
        expect = len(create_ts) + len(bulk) + len(fasts)
        for t in threads:
            t.start()
        deadline = t0 + max(60.0, duration_s * 20)

        def done(stats, lp) -> bool:
            if len(create_ts) >= expect and stats["popped"] == 0 \
                    and lp.settled():
                return True
            if time.monotonic() > deadline:
                raise RuntimeError("fastlane mixed window incomplete")
            return False

        loop.run(done)
        for t in threads:
            t.join(timeout=10)
        return t0, max((create_ts[p.key()] for p in bulk + fasts),
                       default=t0)

    def bulk_sustained(t0: float, offer_end: float) -> float:
        """Median per-interval BULK bind rate over full buckets inside
        the offer window, ramp bucket dropped (run_arrival's contract —
        fast binds are excluded so the bulk tier is measured alone)."""
        n_buckets = int((offer_end - t0) / interval_s) + 1
        intervals = [0] * n_buckets
        for ts, keys in bind_events:
            if not t0 <= ts <= offer_end:
                continue
            b = min(int((ts - t0) / interval_s), n_buckets - 1)
            intervals[b] += sum(1 for k in keys if k not in fast_keys
                                and k in create_ts)
        k_end = int((offer_end - t0) / interval_s)
        steady = intervals[1:k_end] if k_end > 1 \
            else intervals[:max(k_end, 1)]
        return (sorted(steady)[len(steady) // 2] / interval_s) if steady \
            else 0.0

    # quiesce the collector for the measured windows (run_arrival's
    # tuning): in a full bench run this scenario inherits a heap
    # holding a dozen prior scenarios' clusters, and one gen-2 pass
    # mid-window is a 10-20 ms stop-the-world that lands straight in
    # the fast tier's p99 — a collector artifact, not a lane cost
    # (standalone 7.5 ms vs in-suite 17.9 ms before this)
    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        # ---- window 1: solo bulk
        t0_solo, end_solo = offer_window(solo_pods, [])
        solo_rate = bulk_sustained(t0_solo, end_solo)

        # ---- window 2: mixed
        fasts = [fast_pod(i) for i in range(n_fast)]
        fast_keys.update(p.key() for p in fasts)
        t0_mix, end_mix = offer_window(mixed_pods, fasts)
        mixed_rate = bulk_sustained(t0_mix, end_mix)

        # ---- window 3: fast-only probe, counter diff (delta-free proof)
        c0 = {k: v[0] for k, v in _counters.snapshot().items()}
        probes = [fast_pod(n_fast + i) for i in range(probe_pods)]
        fast_keys.update(p.key() for p in probes)
        t0_probe, _ = offer_window([], probes)
        c1 = {k: v[0] for k, v in _counters.snapshot().items()}
    finally:
        gc.enable()
        gc.unfreeze()

    def cdelta(name: str) -> int:
        return int(c1.get(name, 0) - c0.get(name, 0))

    sched.wave_observer = None
    loop.close()

    # ---- fast-tier latency distribution (creator stamp -> bind instant)
    fast_lat, dup, seen = [], 0, set()
    for ts, keys in bind_events:
        for k in keys:
            if k in seen:
                dup += 1
                continue
            seen.add(k)
            if k in fast_keys and k in create_ts:
                fast_lat.append(ts - create_ts[k])
    lat = np.asarray(fast_lat)
    # store truth: every offered pod landed on exactly one node
    placed = {p.name: p.node_name for p in api.list("Pod")[0]}
    unplaced = sum(1 for v in placed.values() if not v)
    fl = {k: int(v[0]) for k, v in _counters.snapshot().items()
          if k.startswith("fastlane.")}
    outcomes = (fl.get("fastlane.bound", 0)
                + fl.get("fastlane.fell_back", 0)
                + fl.get("fastlane.bind_error", 0)
                + fl.get("fastlane.superseded", 0))
    return {
        "fastlane_nodes": n_nodes,
        "fastlane_bulk_rate": float(rate),
        "fastlane_fast_rate": float(fast_rate),
        "fastlane_fast_pods": len(fast_keys),
        "fastlane_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3)
        if lat.size else None,
        "fastlane_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3)
        if lat.size else None,
        "fastlane_bound_via_lane": fl.get("fastlane.bound", 0),
        "fastlane_fell_back": fl.get("fastlane.fell_back", 0),
        "fastlane_bind_errors": fl.get("fastlane.bind_error", 0),
        "fastlane_superseded": fl.get("fastlane.superseded", 0),
        "fastlane_resampled": fl.get("fastlane.resampled", 0),
        "fastlane_dispatch_device": fl.get("fastlane.dispatch_device", 0),
        "fastlane_dispatch_host": fl.get("fastlane.dispatch_host", 0),
        "fastlane_outcomes_partition_ok": bool(
            outcomes == len(fast_keys)),
        "solo_bulk_sustained_pods_s": round(float(solo_rate), 1),
        "mixed_bulk_sustained_pods_s": round(float(mixed_rate), 1),
        "mixed_bulk_sustained": round(mixed_rate / solo_rate, 3)
        if solo_rate else None,
        # delta-free proof over the fast-only probe window: the fast
        # lane never builds an encoding, never walks the full snapshot
        "fastlane_probe_pods": probe_pods,
        "fastlane_probe_encode_builds": cdelta("engine.wave_encode_build"),
        "fastlane_probe_snapshot_rebuilds":
            cdelta("snapshot.refresh_rebuild"),
        "fastlane_probe_snapshot_scans": cdelta("snapshot.refresh_scan"),
        "fastlane_duplicate_binds": int(dup),
        "fastlane_unplaced": int(unplaced),
    }


def run_arrival(n_nodes: int, rate: float, duration_s: float,
                profile: str = "density", pipeline: bool = True,
                budget_ms: float = 250.0, max_burst: int = 0,
                min_quantum: int = 256, max_quantum: int = 16384,
                interval_s: float = 0.0, warm: bool = False,
                churn_cfg=None, mesh_devices: int = 0,
                recorder: bool = False, podtrace: bool = False):
    """THE headline scenario (ISSUE 7): pods are CREATED at a configured
    rate while the ALWAYS-ON loop runs — the reference's density suite
    semantics (test/integration/scheduler_perf/scheduler_test.go:34-39
    per-interval sustained throughput; test/e2e/scalability/density.go:
    316-320 startup latency under churn). The loop owns the scheduler
    (engine/streaming.ScheduleLoop): micro-waves admitted on the
    ``budget_ms`` latency budget, device-resident state warm between
    waves, delta-only refresh. pipeline=False keeps the classic
    synchronous rounds as the debug baseline.

    Honesty contracts (PAPERS.md §Sparrow — offered vs sustained per
    interval is the metric collapse can't hide from):

    - per-pod create->bound is joined from the CREATOR's own stamps and
      the scheduler's per-wave bind instants (Scheduler.wave_observer),
      so the distribution covers the whole span including watch delivery
      — not just what the scheduler saw;
    - ``sustained_pods_s`` is the median per-interval bind rate over
      buckets fully inside the OFFER WINDOW (first bucket dropped as
      ramp) — the post-offer drain is excluded by construction, so a
      batch drain in a streaming costume reports ~0, not its drain rate;
    - ``intervals`` / ``backlog_series`` / ``offered_series`` carry the
      full per-interval story into the JSON artifact;
    - the creator enforces ``max_burst`` (default: ~4 ms of the offered
      rate) and reports its own realized jitter; ``creator_jitter_ok``
      is False when the creator — not the scheduler — was the bottleneck
      or burst source, and high-rate numbers must not be read over it.

    churn_cfg (ISSUE 8): a testing.churn.ChurnConfig turns the quiet-box
    scenario into the CHURN scenario — the same offered stream with a
    seeded fault schedule applied concurrently (node kills/respawns,
    NotReady flaps, cordons, zone relabels, evictions) and bind faults
    injected at the configured rates through FaultyBindApi. The result
    then carries the fault load offered, the requeue/degrade telemetry,
    and an exactly-once audit (zero duplicate bind events)."""
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.ops.predicates import bucket

    total = int(rate * duration_s)
    budget_s = budget_ms / 1e3
    if not interval_s:
        # auto bucket width: at least ~4 full buckets inside the offer
        # window, so `sustained` always has post-ramp full buckets to
        # median over — a short saturation probe with 1s buckets would
        # otherwise fall back to the ramp bucket and under-report
        interval_s = min(1.0, max(0.25, round(duration_s / 4.0, 2)))
    if not max_burst:
        # ~4ms of offered rate per create batch: fine enough that the
        # scheduler sees a stream, coarse enough that time.sleep's ~1ms
        # floor leaves the creator headroom to stay on schedule
        max_burst = max(4, int(rate * 0.004))
    if warm:
        sizes, s = [], min_quantum
        while s <= max_quantum:
            sizes.append(s)
            s *= 2
        _warm_stream_shapes(n_nodes, sizes, profile=profile,
                            mesh_devices=mesh_devices)
    api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + total)))
    nodes = hollow_nodes(n_nodes)
    load_cluster(api, nodes, [])
    injector = None
    if churn_cfg is not None:
        from kubernetes_tpu.testing.churn import (
            ChurnInjector,
            FaultyBindApi,
            make_churn_schedule,
        )
        api = FaultyBindApi(api, fail_rate=churn_cfg.bind_fail_rate,
                            timeout_rate=churn_cfg.bind_timeout_rate,
                            seed=churn_cfg.seed)
        injector = ChurnInjector(api, make_churn_schedule(
            [n.name for n in nodes], churn_cfg, duration_s))
    pods = PROFILES[profile](total)
    pod_index = {p.key(): i for i, p in enumerate(pods)}
    sched = Scheduler(api, record_events=False,
                      mesh=_mesh_or_none(mesh_devices))
    sched.start()
    import numpy as np
    import threading
    loop = None
    if pipeline:
        # seed the quantum near the budget's steady state so the doubling
        # ramp (one compiled shape per step) happens in the warm ladder,
        # not across the first offered seconds
        seed = bucket(max(min_quantum, min(int(rate * budget_s / 4),
                                           max_quantum)))
        loop = sched.stream(budget_s=budget_s, min_quantum=min_quantum,
                            max_quantum=max_quantum, chunk=seed)
    if warm:
        # prime THIS scheduler's resident state before the offer window:
        # an always-on loop has been running forever when a pod arrives —
        # charging the one-time boot (first snapshot build, full device
        # upload, encoding + precompute construction) to the first
        # arrivals would measure boot, not the stream. Prime pods are
        # excluded from every reported number (they are not in pod_index).
        for p in PROFILES[profile](min(64, min_quantum)):
            p.name = "prime-" + p.name
            api.create("Pod", p)
        if loop is not None:
            loop.drain()  # the shared quiesce predicate (incl. the
            # backoff heap): a prime pod requeued off a transient error
            # must bind BEFORE the observer arms, or its late bind event
            # would leak into the measured interval series
        else:
            while sched.schedule_round()["popped"] or \
                    sched.queue.ready_count() or sched.queue._deferred:
                pass
    # counter baseline at the OFFER-WINDOW boundary: warmup (shape-ladder
    # drains + this scheduler's own prime/boot encoding build) is all
    # behind this point, so consumers reading span-counter invariants
    # ("zero encode rebuilds during the stream", delta rows shipped)
    # diff against this instead of a pre-warm reset that can never show
    # the delta-only invariant
    from kubernetes_tpu.utils.trace import COUNTERS as _counters
    counters_at_offer_start = {
        k: v[0] for k, v in _counters.snapshot().items()}
    # quiesce the collector for the measured window (same tuning as the
    # drain headline): a gen-2 pass over the warm heap mid-offer is a
    # 200-400ms stop-the-world that reads as a scheduler latency spike
    # AND a creator burst — both lies about the engine
    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    # flight recorder (ISSUE 13): armed for the measured window only —
    # the recorder-on leg of the telemetry-overhead A/B. The warm/prime
    # phases above ran with it off, so the ring holds exactly the
    # offered stream's waves. recorder=False FORCE-disables for the
    # window (restored after): with GRAFT_FLIGHT_RECORDER=1 in the env
    # the off arm would otherwise silently record too, and the A/B
    # would compare on-vs-on — a vacuous pass of the overhead bar.
    from kubernetes_tpu.observability.recorder import RECORDER as _flight
    _flight_was = _flight.enabled
    if recorder:
        _flight.clear()
        _flight.enable()
    else:
        _flight.disable()
    # pod-level black box (ISSUE 15): the podtrace+SLO arm of ITS on/off
    # A/B — armed for the measured window only (warm/prime pods never
    # enter a timeline), force-disabled on the off arm so an env-armed
    # tracer cannot turn the A/B into on-vs-on
    from kubernetes_tpu.observability.podtrace import TRACER as _tracer
    from kubernetes_tpu.observability.slo import SLO as _slo
    _tracer_was = _tracer.enabled
    _slo_was = _slo.enabled
    if podtrace:
        _tracer.clear()
        _tracer.enable()
        _slo.clear()
        _slo.enable()
    else:
        _tracer.disable()
        _slo.disable()
    created = [0]
    create_ts = np.full(total, -1.0)   # per-pod create instant, rel. t0
    create_log = []                    # (t_rel, batch_size) per burst
    bind_events = []                   # (t_rel, [pod keys]) per bind pass
    t0 = time.monotonic()
    sched.wave_observer = lambda ts, keys: bind_events.append((ts - t0,
                                                               keys))

    def creator():
        # offered-rate creator on its OWN thread: a wave that outlives
        # 1/rate must not stall arrivals, or the "rate-driven" scenario
        # silently degrades back into bursty pre-loaded batches.
        # ApiServerLite.create is lock-protected, so this races the
        # scheduler safely. max_burst bounds how many pods one wakeup may
        # create — at 20k/s the old 10ms sleep floor turned the "stream"
        # into 200-pod bursts that measured the creator, not the scheduler.
        while created[0] < total:
            now = time.monotonic() - t0
            due = min(total, int(rate * now), created[0] + max_burst)
            if due > created[0]:
                for p in pods[created[0]:due]:
                    api.create("Pod", p)
                ts = time.monotonic() - t0
                create_ts[created[0]:due] = ts
                create_log.append((ts, due - created[0]))
                created[0] = due
            next_due = t0 + (created[0] + 1) / rate
            delay = next_due - time.monotonic()
            if delay > 0:
                time.sleep(min(delay, max(0.0005, max_burst / rate / 4)))

    creator_thread = threading.Thread(target=creator, daemon=True)
    creator_thread.start()
    churn_stop = None
    churn_thread = None
    if injector is not None:
        churn_stop = threading.Event()
        churn_thread = injector.run_thread(churn_stop, t0=t0)
    # wall-clock safety net, NOT a round budget: a round-count backstop
    # silently truncates low-rate runs (empty rounds take microseconds),
    # returning a plausible-looking JSON over a partial window. Churn
    # runs get more rope: backoff-requeued rows (liveness rejects, bind
    # faults) legitimately wait out their delay in the drain tail.
    deadline = t0 + max(60.0, duration_s * 20) \
        + (120.0 if injector is not None else 0.0)
    backlog_at_offer_end = [None]
    backlog_samples = []               # (t_rel, queued + in-flight)
    quantum_peak = [0]
    last_sample = [0.0]

    def _backlog(loop) -> int:
        inflight = 0
        if loop is not None and loop.inflight is not None:
            inflight = len(loop.inflight.pods)
        return len(sched.queue) + inflight

    agg = {"bind_errors": 0, "fence_requeued": 0, "liveness_requeued": 0,
           "degraded_steps": 0}

    def note(stats, loop):
        now = time.monotonic() - t0
        for k in agg:
            agg[k] += stats.get(k, 0)
        if loop is not None:
            quantum_peak[0] = max(quantum_peak[0], loop.quantum)
        if now - last_sample[0] >= 0.05 or stats["bound"]:
            backlog_samples.append((now, _backlog(loop)))
            last_sample[0] = now
        if backlog_at_offer_end[0] is None and created[0] >= total:
            # the offered stream just ended: whatever is still queued or
            # mid-pipeline is the backlog the scheduler could not keep
            # up with
            backlog_at_offer_end[0] = _backlog(loop)

    def done(stats, loop) -> bool:
        # loop.settled() is the shared quiesce predicate (pipeline idle,
        # watch drained, ready queue AND backoff heap empty — a deferred
        # pod is retriable and abandoning it would report percentiles
        # over a silently partial population); truly-unschedulable pods
        # never stop re-entering, so the wall-clock deadline below still
        # bounds the run
        if created[0] >= total and stats["popped"] == 0 \
                and (loop.settled() if loop is not None
                     else (sched.sync() == 0
                           and sched.queue.ready_count() == 0
                           and not sched.queue._deferred)):
            return True
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"arrival run incomplete after {deadline - t0:.0f}s: "
                f"created {created[0]}/{total}, bound "
                f"{sum(len(ks) for _, ks in bind_events)}")
        return False

    try:
        if loop is not None:
            try:
                loop.run(done, on_step=note)
            finally:
                loop.close()
        else:
            # classic synchronous rounds: the debug/A-B baseline
            while True:
                stats = sched.schedule_round()
                note(stats, None)
                if done(stats, None):
                    break
                if stats["popped"] == 0 and stats["bound"] == 0:
                    sched.sync(wait=0.002)
    finally:
        gc.enable()
        gc.unfreeze()
        # restore the PRE-leg state either way: the on arm armed it for
        # the window, the off arm force-disabled it — an env-armed
        # recorder (GRAFT_FLIGHT_RECORDER=1) stays armed for whatever
        # runs next in this process
        _flight.enabled = _flight_was
        _tracer.enabled = _tracer_was
        _slo.enabled = _slo_was
        if churn_stop is not None:
            churn_stop.set()
    creator_thread.join(timeout=10)
    if churn_thread is not None:
        churn_thread.join(timeout=10)
    sched.wave_observer = None

    # ---- per-pod create->bound joined from creator stamps + bind instants
    # (plus the exactly-once audit: the store refuses double binds, so a
    # pod key appearing in TWO bind-observer passes would mean the engine
    # bound the same pod twice — the invariant injected faults must not
    # break)
    lat = np.full(total, -1.0)
    bound = 0
    duplicate_binds = 0
    seen_bound = set()
    for ts, keys in bind_events:
        for k in keys:
            if k in seen_bound:
                duplicate_binds += 1
                continue
            seen_bound.add(k)
            i = pod_index.get(k)
            if i is None:
                continue  # prime pod / retry echo: not in the offer
            bound += 1
            if create_ts[i] >= 0:
                lat[i] = ts - create_ts[i]
    lat = lat[lat >= 0]
    # reconcile against STORE truth: a landed-but-timed-out bind (the
    # injected at-most-once ambiguity) is bound in the store but never
    # reached the observer — it must count as bound (it is not lost),
    # it just has no honest latency sample. Evicted pods bound before
    # their eviction keep their observer sample.
    if injector is not None:
        api_state = {p.key(): bool(p.node_name)
                     for p in api.list("Pod")[0]}
        bound = sum(1 for p in pods if api_state.get(p.key(), True))

    # ---- per-interval series: binds at bind instants, backlog sampled,
    # offered from the creator's own log; FULL buckets only — the partial
    # remainder rides in `tail_partial`, not the series (ISSUE 18)
    offer_end = create_log[-1][0] if create_log else 0.0
    intervals, offered_series, backlog_series, tail_partial = \
        interval_series(bind_events, create_log, backlog_samples,
                        interval_s)
    # sustained = median bind rate over buckets FULLY inside the offer
    # window, first bucket dropped as ramp — NO post-offer-drain
    # averaging: a run that binds nothing while offered and drains fast
    # afterwards (the r09 shape) reports ~0 here, exactly as it should
    k_end = int(offer_end / interval_s)  # first PARTIAL bucket
    steady = intervals[1:k_end] if k_end > 1 else intervals[:max(k_end, 1)]
    sustained = (sorted(steady)[len(steady) // 2] / interval_s) if steady \
        else 0.0

    # ---- creator self-audit: did the measurement stream what it claims?
    lags = [ts - n_done / rate for (ts, _), n_done in
            zip(create_log, np.cumsum([n for _, n in create_log]))]
    lag_p99_ms = float(np.percentile(lags, 99) * 1e3) if lags else 0.0
    realized_rate = total / offer_end if offer_end > 0 else 0.0
    # bound: two max_burst periods of schedule lag, floored at 100ms — a
    # transient GIL hold with bounded catch-up bursts still streams
    # (burst size is capped by construction); SUSTAINED creator collapse
    # shows up as realized rate falling under the offer
    lag_bound_ms = max(2e3 * max_burst / rate, 100.0)
    jitter_ok = bool(lag_p99_ms <= lag_bound_ms
                     and realized_rate >= 0.95 * rate)

    out = {
        "intervals": [int(v) for v in intervals],
        "interval_s": interval_s,
        "offered_series": [int(v) for v in offered_series],
        "backlog_series": [int(v) for v in backlog_series],
        "tail_partial": tail_partial,
        "offered_pods_s": float(rate),
        "offered_realized_pods_s": round(realized_rate, 1),
        "sustained_pods_s": round(float(sustained), 1),
        "p50_ms": float(np.percentile(lat, 50) * 1e3) if lat.size else None,
        "p99_ms": float(np.percentile(lat, 99) * 1e3) if lat.size else None,
        "bound": int(bound),
        "backlog_at_offer_end": int(backlog_at_offer_end[0] or 0),
        "unbound": total - int(bound),
        "budget_ms": float(budget_ms),
        "quantum_peak": int(quantum_peak[0]),
        "creator_max_burst": int(max_burst),
        "creator_lag_p99_ms": round(lag_p99_ms, 3),
        "creator_lag_bound_ms": round(lag_bound_ms, 3),
        "creator_jitter_ok": jitter_ok,
        # robustness telemetry (ISSUE 8): bind errors now travel with
        # every arrival number (injected faults MUST increment this), and
        # the fence/degrade story is visible next to the throughput it
        # protected
        "bind_errors": int(agg["bind_errors"]),
        "fence_requeued": int(agg["fence_requeued"]),
        "liveness_requeued": int(agg["liveness_requeued"]),
        "degraded_steps": int(agg["degraded_steps"]),
        "duplicate_binds": int(duplicate_binds),
        "counters_at_offer_start": counters_at_offer_start,
    }
    if recorder:
        out["recorder_events"] = int(_flight.stats()["events"])
        out["recorder_dropped"] = int(_flight.stats()["dropped"])
    if podtrace:
        # tail-forensics demo (ISSUE 15 acceptance): slowest-K exemplar
        # timelines of THIS offered stream, each with its per-phase
        # attribution and the telescoping check (phase sums == the
        # pod's create->bound span within stamp resolution)
        psnap = _tracer.snapshot()
        # ISSUE 20 satellite: the slowest-K reservoir of a saturated
        # stream is dominated by near-identical timelines — siblings of
        # the same wave walking the same phase sequence. Keep ONE
        # exemplar per (wave id, phase signature), the slowest of its
        # group (the reservoir is span-sorted), with a multiplicity
        # count and the group's span range. Every KEPT exemplar still
        # carries its own full phase decomposition, so the telescoping
        # guarantee (phase sums == create->bound) is asserted per
        # exemplar exactly as before — dedupe drops rows, never phases.
        exemplars = []
        seen: dict = {}
        for ex in psnap["exemplars"]:
            ssum = sum(ex["phases_ms"].values())
            wave = next((e["a"] for e in ex["events"]
                         if e["kind"] == "WAVE_DISPATCHED"), None)
            sig = (wave, tuple(e["kind"] for e in ex["events"]))
            if sig in seen:
                g = seen[sig]
                g["multiplicity"] += 1
                g["span_ms_range"][0] = min(g["span_ms_range"][0],
                                            ex["span_ms"])
                g["span_ms_range"][1] = max(g["span_ms_range"][1],
                                            ex["span_ms"])
                continue
            seen[sig] = row = {
                "key": ex["key"],
                "wave": wave,
                "create_to_bound_ms": ex["span_ms"],
                "phases_ms": ex["phases_ms"],
                "phase_sum_ms": round(ssum, 6),
                "attribution_exact":
                    bool(abs(ssum - ex["span_ms"]) < 1e-3),
                "events": [e["kind"] for e in ex["events"]],
                "multiplicity": 1,
                "span_ms_range": [ex["span_ms"], ex["span_ms"]],
            }
            exemplars.append(row)
        out["podtrace"] = {
            "stats": psnap["stats"],
            "phases": psnap["phases"],
            "tail_exemplars": exemplars,
            "tail_exemplars_raw": len(psnap["exemplars"]),
            "slo": _slo.snapshot(),
        }
    if injector is not None:
        out.update({
            "churn_ops_applied": dict(injector.applied),
            "churn_ops_noop": int(injector.noop),
            "injected_bind_failures": int(api.injected_failures),
            "injected_bind_timeouts": int(api.injected_timeouts),
        })
    return out


def arrival_sweep(n_nodes: int, rates, budget_ms: float = 250.0,
                  profile: str = "density", pods_cap: int = 60_000):
    """Offered-rate sweep: run_arrival at each rate on a fresh cluster,
    duration clamped so the pod population stays bounded. Returns
    {rate: trimmed result} for the artifact — the per-rate interval series
    make over-saturation VISIBLE (backlog ramps, sustained flatlines below
    offered) instead of averaged away."""
    out = {}
    for rate in rates:
        duration = max(1.5, min(6.0, pods_cap / rate))
        r = run_arrival(n_nodes, rate=rate, duration_s=duration,
                        profile=profile, budget_ms=budget_ms, warm=True)
        out[str(int(rate))] = {k: r[k] for k in (
            "offered_pods_s", "sustained_pods_s", "p50_ms", "p99_ms",
            "bound", "unbound", "backlog_at_offer_end", "intervals",
            "backlog_series", "quantum_peak", "creator_jitter_ok")}
    return out


def saturation_search(n_nodes: int, budget_ms: float = 250.0,
                      lo: float = 10_000, hi: float = 48_000,
                      probe_s: float = 2.5, profile: str = "density"):
    """Max offered rate the engine SUSTAINS under the latency budget:
    galloping search upward from `lo` while probes pass (p99 under
    budget, sustained >= 95% of offered, nothing left unbound), then one
    bisection step between the last pass and first fail. Returns the
    probe log plus max_sustained_pods_s — the single number the paper's
    'how fast is it really' question wants, measured instead of implied."""
    probes = []

    def passes(rate):
        duration = max(1.5, min(probe_s, 60_000 / rate))
        r = run_arrival(n_nodes, rate=rate, duration_s=duration,
                        profile=profile, budget_ms=budget_ms, warm=True)
        ok = bool(r["p99_ms"] is not None and r["p99_ms"] < budget_ms
                  and r["sustained_pods_s"] >= 0.95 * rate
                  and r["unbound"] == 0)
        probes.append({"rate": float(rate), "ok": ok,
                       "sustained_pods_s": r["sustained_pods_s"],
                       "p99_ms": round(r["p99_ms"], 3)
                       if r["p99_ms"] is not None else None,
                       "creator_jitter_ok": r["creator_jitter_ok"]})
        return ok

    best, fail = 0.0, None
    rate = lo
    while rate <= hi:
        if passes(rate):
            best = rate
            rate = rate * 1.5
        else:
            fail = rate
            break
    if best and fail:
        mid = (best + fail) / 2
        if mid - best > 0.1 * best and passes(mid):
            best = mid
    return {"max_sustained_pods_s": float(best), "budget_ms": budget_ms,
            "probes": probes}


def measure_churn(n_nodes: int, rate: float, duration_s: float,
                  budget_ms: float = 250.0, profile: str = "churn"):
    """THE ISSUE 8 scenario: the arrival stream measured twice on the same
    box — once quiet, once under the seeded `churn` fault schedule
    (ROADMAP shape: sustained 10%/min node churn + NotReady flaps +
    cordons + zone relabels + evictions + injected bind failures AND
    landed-but-timed-out binds) — and reported as a RATIO, so the number
    is "how much of the quiet throughput survives production-rate faults"
    rather than an absolute a different box can't compare. Alongside the
    ratio travel the counters that prove HOW it survived: Protean patch
    rows vs wholesale rebuilds (the acceptance bound: rebuilds stay
    O(vocab/class growth), not O(foreign binds)), liveness-fence
    requeues (rows that would have bound into ghosts), degraded-mode
    transitions, and the exactly-once audit (zero duplicate binds under
    injected bind faults)."""
    from kubernetes_tpu.testing.churn import ChurnConfig
    from kubernetes_tpu.utils.trace import COUNTERS

    quiet = run_arrival(n_nodes, rate=rate, duration_s=duration_s,
                        profile=profile, budget_ms=budget_ms, warm=True)
    cfg = ChurnConfig(
        seed=int(os.environ.get("BENCH_CHURN_SEED", "11")),
        node_churn_per_min=float(
            os.environ.get("BENCH_CHURN_NODE_PCT_MIN", "0.10")),
        bind_fail_rate=float(
            os.environ.get("BENCH_CHURN_BIND_FAIL", "0.002")),
        bind_timeout_rate=float(
            os.environ.get("BENCH_CHURN_BIND_TIMEOUT", "0.001")))
    COUNTERS.reset()
    churned = run_arrival(n_nodes, rate=rate, duration_s=duration_s,
                          profile=profile, budget_ms=budget_ms, warm=True,
                          churn_cfg=cfg)
    snap = COUNTERS.snapshot()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    quiet_s = quiet["sustained_pods_s"]
    churn_s = churned["sustained_pods_s"]
    # the exactly-once invariant is a hard gate, like the gang-atomicity
    # raise: numbers over a double bind are not numbers
    if churned["duplicate_binds"] or quiet["duplicate_binds"]:
        raise RuntimeError(
            f"duplicate binds: quiet={quiet['duplicate_binds']} "
            f"churn={churned['duplicate_binds']}")
    # cpus-aware bar + same-box attribution (ISSUE 20 satellite): the
    # r11 >=0.5 bar was set where fault housekeeping could OVERLAP the
    # stream core. On a 1-core box every rebuild/requeue serializes
    # behind the stream, so the ratio sits structurally lower. The
    # placebo arm separates harness cost from fault-handling cost: the
    # SAME churn machinery (FaultyBindApi wrapper + injector thread)
    # with an all-zero fault schedule — if the placebo ratio holds near
    # 1.0, the collapse is real fault work with no spare core to hide
    # on, not the measurement apparatus.
    cpus = os.cpu_count() or 1
    bar = 0.5 if cpus >= 2 else 0.35
    attribution = {"cpus": cpus, "bar": bar, "r11_bar_cpus": 2}
    if cpus == 1 and os.environ.get("BENCH_CHURN_ATTRIBUTION",
                                    "1") != "0":
        placebo_cfg = ChurnConfig(
            seed=cfg.seed, node_churn_per_min=0.0, flap_per_min=0.0,
            cordon_per_min=0.0, relabel_per_min=0.0,
            evict_per_min_abs=0.0, bind_fail_rate=0.0,
            bind_timeout_rate=0.0)
        placebo = run_arrival(n_nodes, rate=rate, duration_s=duration_s,
                              profile=profile, budget_ms=budget_ms,
                              warm=True, churn_cfg=placebo_cfg)
        placebo_ratio = (placebo["sustained_pods_s"] / quiet_s
                         if quiet_s else 0.0)
        attribution["placebo_ratio"] = round(placebo_ratio, 3)
        attribution["verdict"] = (
            "fault-handling serializes behind the single stream core "
            "(placebo churn harness keeps quiet throughput)"
            if placebo_ratio >= 0.85 else
            "churn harness thread itself contends for the stream core")
    return {
        "churn_cpus": cpus,
        "churn_vs_quiet_bar": bar,
        "churn_attribution": attribution,
        "churn_offered_pods_s": float(rate),
        "churn_quiet_sustained_pods_s": quiet_s,
        "churn_sustained_pods_s": churn_s,
        "churn_vs_quiet": round(churn_s / quiet_s, 3) if quiet_s else 0.0,
        "churn_p99_create_to_bound_ms": round(churned["p99_ms"], 3)
        if churned["p99_ms"] is not None else None,
        "churn_bound": churned["bound"],
        "churn_unbound": churned["unbound"],
        "churn_bind_errors": churned["bind_errors"],
        "churn_injected_bind_failures": churned.get(
            "injected_bind_failures", 0),
        "churn_injected_bind_timeouts": churned.get(
            "injected_bind_timeouts", 0),
        "churn_duplicate_binds": churned["duplicate_binds"],
        "churn_ops_applied": churned.get("churn_ops_applied", {}),
        "churn_liveness_requeued": churned["liveness_requeued"],
        "churn_fence_requeued": churned["fence_requeued"],
        "churn_degraded_steps": churned["degraded_steps"],
        # Protean invalidation observability (ISSUE 8 acceptance):
        # patch rows O(foreign churn), full rebuilds O(vocab growth)
        "churn_aff_patch_rows": cnt("engine.aff_patch_rows"),
        "churn_aff_full_rebuilds": cnt("engine.aff_full_rebuilds"),
        "churn_label_patch_rows": cnt("engine.label_patch_rows"),
        "churn_liveness_fence_requeues":
            cnt("engine.liveness_fence_requeues"),
        "churn_degraded_enter": cnt("stream.degraded_enter"),
        "churn_degraded_exit": cnt("stream.degraded_exit"),
    }


def measure_rolling_update(n_nodes: int = 256, replicas: int = 400,
                           max_surge: int = 40, max_unavailable: int = 40,
                           bg_rate: float = 1500.0,
                           diurnal_amp: float = 0.5,
                           diurnal_period_s: float = 3.0,
                           budget_ms: float = 250.0) -> dict:
    """THE ISSUE 18 scenario: a deployment-shaped rolling update —
    evict-and-recreate waves under maxSurge/maxUnavailable bounds —
    riding a diurnal background offered-rate curve through the SAME
    always-on loop. The update's replacement pods are deploy-shaped
    traffic: they arrive in controller-paced bursts gated on earlier
    replacements binding, exactly the feedback loop a batch scheduler's
    drain rate hides.

    Reported: update completion time (controller start -> last
    replacement bound), p50/p99 create->bound of REPLACEMENT pods on
    the loaded stream (acceptance: p99 < 250 ms, read with the box's
    documented ±30% noise and the `cpus` disclosure), the measured
    surge/unavailability extremes with respected booleans, and the
    store-truth audits — zero duplicate binds (observer join), every
    replacement bound exactly once (event-log transitions), and the
    cache-vs-store ghost audit after quiesce. The scenario RAISES on
    any broken invariant: numbers over a ghost bind are not numbers."""
    import threading

    import numpy as np

    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.testing.churn import (
        RollingUpdateConfig,
        RollingUpdateDriver,
        audit_cache_vs_store,
        audit_store_transitions,
        diurnal_rate,
    )

    budget_s = budget_ms / 1e3
    # background population bound: the diurnal curve integrates to ~base
    # over full periods; cap the run so the cluster never saturates
    # (replicas + surge + background must fit with headroom — a full
    # cluster would measure unschedulability, not the update)
    bg_cap = int(bg_rate * 12.0)
    need = replicas + max_surge + bg_cap + 64
    n_nodes = max(n_nodes, -(-need // 36))
    _warm_stream_shapes(n_nodes, [64, 128, 256], profile="density")
    api = ApiServerLite(max_log=max(400_000, 6 * (n_nodes + need)))
    load_cluster(api, hollow_nodes(n_nodes), [])
    sched = Scheduler(api, record_events=False)
    sched.start()
    loop = sched.stream(budget_s=budget_s, min_quantum=64,
                        max_quantum=256)

    def web_pod(rev: str, i: int):
        return make_pod(f"web-{rev}-{i:05d}", cpu=100, memory=128 << 20,
                        labels={"app": "web", "rev": rev})

    # old revision fully bound BEFORE the window: a rolling update
    # replaces a RUNNING deployment (binding the old revision also warms
    # this scheduler's resident state, so boot cost stays out of the
    # measured completion time)
    for i in range(replicas):
        api.create("Pod", web_pod("1", i))
    loop.drain()
    old_bound = sum(1 for p in api.list("Pod")[0]
                    if p.labels.get("rev") == "1" and p.node_name)
    if old_bound != replicas:
        raise RuntimeError(
            f"rolling update pre-state incomplete: {old_bound}/{replicas}"
            " old-revision pods bound")

    bind_events = []               # (t_abs, [keys]) across the window
    sched.wave_observer = lambda ts, keys: bind_events.append((ts, keys))
    cfg = RollingUpdateConfig(replicas=replicas, max_surge=max_surge,
                              max_unavailable=max_unavailable)
    driver = RollingUpdateDriver(api, cfg,
                                 lambda i: web_pod("2", i))
    rate_fn = diurnal_rate(bg_rate, amp=diurnal_amp,
                           period_s=diurnal_period_s)
    bg_pods = PROFILES["density"](bg_cap)
    for p in bg_pods:
        p.name = "bgload-" + p.name
    bg_created = [0]
    stop = threading.Event()
    t0 = time.monotonic()

    def bg_creator():
        # diurnal offered stream: numerically integrate rate(t) so the
        # realized curve follows the sinusoid, not its mean
        due_f, last = 0.0, time.monotonic()
        while not stop.is_set() and bg_created[0] < len(bg_pods):
            now = time.monotonic()
            due_f += rate_fn(now - t0) * (now - last)
            last = now
            due = min(int(due_f), len(bg_pods))
            while bg_created[0] < due:
                api.create("Pod", bg_pods[bg_created[0]])
                bg_created[0] += 1
            stop.wait(0.002)

    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    bg_thread = threading.Thread(target=bg_creator, daemon=True)
    bg_thread.start()
    upd_thread = driver.run_thread(stop, poll_s=0.005)
    deadline = t0 + 120.0

    def done(stats, lp) -> bool:
        if driver.completed_at is not None:
            stop.set()  # update finished: stop the background offer too
            if stats["popped"] == 0 and lp.settled() \
                    and not bg_thread.is_alive():
                return True
        if time.monotonic() > deadline:
            raise RuntimeError(
                "rolling update incomplete after 120s: "
                f"{driver.bounds_report()}")
        return False

    try:
        loop.run(done)
        # drain whatever background pods landed after the update closed
        loop.drain()
    finally:
        gc.enable()
        gc.unfreeze()
        stop.set()
    upd_thread.join(timeout=10)
    bg_thread.join(timeout=10)
    sched.wave_observer = None

    # ---- replacement create->bound joined the run_arrival way, plus the
    # observer-side exactly-once audit over EVERY key in the window
    repl_keys = set(driver.replacement_keys)
    lat, dup, seen, last_repl_bind = [], 0, set(), t0
    for ts, keys in bind_events:
        for k in keys:
            if k in seen:
                dup += 1
                continue
            seen.add(k)
            if k in repl_keys:
                lat.append(ts - driver.create_ts[k])
                last_repl_bind = max(last_repl_bind, ts)
    bounds = driver.bounds_report()
    # store-truth audits (the hard gates)
    trans = audit_store_transitions(api)
    repl_multi_binds = sum(1 for k, c in trans["binds"].items()
                           if k in repl_keys and c != 1)
    ghosts = audit_cache_vs_store(sched, api)
    loop.close()
    if dup or repl_multi_binds or ghosts:
        raise RuntimeError(
            f"rolling update broke exactly-once: duplicate_binds={dup} "
            f"replacement_multi_binds={repl_multi_binds} "
            f"cache_vs_store={ghosts[:3]}")
    unbound_repl = replicas - sum(
        1 for k in repl_keys if trans["binds"].get(k, 0) == 1)
    lat_a = np.asarray(lat)
    return {
        "rolling_update_completion_s": round(
            (driver.completed_at or last_repl_bind) - driver.started_at, 3)
        if driver.started_at else None,
        "rolling_replicas": replicas,
        "rolling_replacement_p50_ms": round(
            float(np.percentile(lat_a, 50)) * 1e3, 3) if lat else None,
        "rolling_replacement_p99_ms": round(
            float(np.percentile(lat_a, 99)) * 1e3, 3) if lat else None,
        "rolling_replacements_bound": int(len(lat)),
        "rolling_replacements_unbound": int(unbound_repl),
        "rolling_bounds": bounds,
        "rolling_surge_respected": bounds["surge_respected"],
        "rolling_unavailable_respected": bounds["unavailable_respected"],
        "rolling_evictions": bounds["evicted"],
        "rolling_bg_offered_pods_s": float(bg_rate),
        "rolling_bg_diurnal_amp": float(diurnal_amp),
        "rolling_bg_created": int(bg_created[0]),
        "rolling_duplicate_binds": int(dup),
        "rolling_ghost_binds": 0,
        "rolling_budget_ms": float(budget_ms),
    }


def measure_priority_churn(n_nodes: int = 240, rate: float = 2000.0,
                           duration_s: float = 4.0,
                           budget_ms: float = 250.0,
                           drain_s: float = 0.0,
                           evict_fail_rate: float = 0.02,
                           evict_timeout_rate: float = 0.01,
                           max_evictions_per_min: int = 6000):
    """THE ISSUE 14 scenario: an OVERCOMMITTED cluster under a mixed-band
    arrival stream — offered pods exceed capacity by design, so the high
    bands can only land by displacing the low bands through the wave
    path's atomic preemption, under injected eviction FAILURES and
    landed-but-timed-out evictions on the victim-delete seam.

    Reported: preemption-latency percentiles (propose -> atomic
    commit-complete per committed preemption), victims-per-preemption,
    commit/rollback/budget counters, per-band bound fractions at the
    end, and the hard audits — the scenario RAISES (numbers over a
    broken invariant are not numbers) on any duplicate bind, any
    double-eviction or ghost victim against store truth, or any sliding
    60 s window exceeding the configured disruption budget."""
    import threading

    import numpy as np

    from kubernetes_tpu.engine.preempt_wave import DisruptionBudget
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import (
        PRIORITY_BANDS,
        PROFILES,
        hollow_nodes,
        load_cluster,
    )
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.testing.churn import (
        FaultyBindApi,
        audit_cache_vs_store,
        audit_store_transitions,
    )
    from kubernetes_tpu.utils import features
    from kubernetes_tpu.utils.trace import COUNTERS

    total = int(rate * duration_s)
    if not drain_s:
        drain_s = max(6.0, duration_s)
    min_q, max_q = 256, 2048
    # the wave-shape ladder compiles with the gate OFF (run_until_drained
    # routes PodPriority drains classic, which would skip the wave jits)
    sizes, s = [], min_q
    while s <= max_q:
        sizes.append(s)
        s *= 2
    _warm_stream_shapes(n_nodes, sizes, profile="priority_churn")
    features.DEFAULT_FEATURE_GATE.set("PodPriority", True)
    try:
        api = ApiServerLite(max_log=max(400_000, 6 * (n_nodes + total)))
        nodes = hollow_nodes(n_nodes)
        load_cluster(api, nodes, [])
        api = FaultyBindApi(api, seed=7,
                            evict_fail_rate=evict_fail_rate,
                            evict_timeout_rate=evict_timeout_rate)
        pods = PROFILES["priority_churn"](total)
        pod_prio = {p.key(): p.priority for p in pods}
        sched = Scheduler(api, record_events=False)
        sched.disruption_budget = DisruptionBudget(
            max_evictions_per_min=max_evictions_per_min)
        sched.start()
        loop = sched.stream(budget_s=budget_ms / 1e3, min_quantum=min_q,
                            max_quantum=max_q)
        # compile the victim-scan jit before the measured window
        sched.engine._refresh()
        probe = PROFILES["priority_churn"](1)[0]
        sched.engine.preempt_scan([probe])
        counters0 = {k: v[0] for k, v in COUNTERS.snapshot().items()}
        created = [0]
        bind_events = []
        plog = []  # (t_rel, latency_s, victims) per committed preemption
        t0 = time.monotonic()
        sched.wave_observer = lambda ts, keys: bind_events.append(
            (ts - t0, keys))
        sched.preempt_observer = lambda ts, lat, nv: plog.append(
            (ts - t0, lat, nv))
        max_burst = max(4, int(rate * 0.004))

        def creator():
            while created[0] < total:
                now = time.monotonic() - t0
                due = min(total, int(rate * now), created[0] + max_burst)
                if due > created[0]:
                    for p in pods[created[0]:due]:
                        api.create("Pod", p)
                    created[0] = due
                delay = t0 + (created[0] + 1) / rate - time.monotonic()
                if delay > 0:
                    time.sleep(min(delay, 0.002))

        th = threading.Thread(target=creator, daemon=True)
        th.start()
        t_stop = t0 + duration_s + drain_s
        agg = {"degraded_steps": 0, "preemptions": 0,
               "preempt_rollbacks": 0, "victims_evicted": 0,
               "budget_deferred": 0}

        def note(stats, _loop):
            for k in agg:
                agg[k] += stats.get(k, 0)

        def done(stats, _loop) -> bool:
            # an overcommitted cluster never settles (the displaced low
            # bands legitimately wait forever) — the stop is wall-clock
            return created[0] >= total and time.monotonic() >= t_stop

        try:
            loop.run(done, on_step=note)
        finally:
            loop.close()
        th.join(timeout=10)
        sched.sync()  # drain the final watch events before auditing
        sched.wave_observer = None
        sched.preempt_observer = None
        counters1 = {k: v[0] for k, v in COUNTERS.snapshot().items()}

        def cnt(name):
            return counters1.get(name, 0) - counters0.get(name, 0)

        # ---- hard audits -------------------------------------------
        # duplicate binds reconcile against STORE truth: an evicted
        # victim that later REBINDS is the starvation guard working (two
        # observer events, two store binds with an eviction between) —
        # a duplicate is the scheduler REPORTING more binds for a pod
        # than the store ever accepted
        trans = audit_store_transitions(api)
        observed: dict = {}
        for _ts, keys in bind_events:
            for k in keys:
                observed[k] = observed.get(k, 0) + 1
        dup = sum(max(0, c - trans["binds"].get(k, 0))
                  for k, c in observed.items())
        over_evicted = [k for k, c in trans["evicts"].items()
                        if c > trans["binds"].get(k, 0)]
        ghosts = audit_cache_vs_store(sched, api)
        # sliding-window budget check over the actual eviction instants
        evict_ts = sorted(t for t, _lat, nv in plog for _ in range(nv))
        window_peak = 0
        j = 0
        for i, t in enumerate(evict_ts):
            while evict_ts[j] <= t - DisruptionBudget.WINDOW_S:
                j += 1
            window_peak = max(window_peak, i - j + 1)
        if dup or over_evicted or ghosts \
                or window_peak > max_evictions_per_min:
            raise RuntimeError(
                f"priority_churn invariant broken: duplicate_binds={dup} "
                f"double_evictions={len(over_evicted)} "
                f"ghost_discrepancies={ghosts[:5]} "
                f"budget_window_peak={window_peak}/"
                f"{max_evictions_per_min}")
        # ---- per-band outcome against store truth ------------------
        store_bound = {p.key() for p in api.list("Pod")[0]
                       if p.node_name}
        band_of = {v: k for k, v in PRIORITY_BANDS.items()}
        band_tot: dict = {}
        band_bnd: dict = {}
        for p in pods:
            b = band_of.get(pod_prio[p.key()], "other")
            band_tot[b] = band_tot.get(b, 0) + 1
            if p.key() in store_bound:
                band_bnd[b] = band_bnd.get(b, 0) + 1
        lats = np.array([lat for _t, lat, _nv in plog])
        vics = np.array([nv for _t, _lat, nv in plog])
        n_commit = len(plog)
        return {
            "prio_offered_pods": total,
            "prio_nodes": n_nodes,
            "prio_offered_pods_s": float(rate),
            "prio_bound": len(store_bound),
            "prio_band_bound_fraction": {
                b: round(band_bnd.get(b, 0) / band_tot[b], 3)
                for b in band_tot},
            "prio_preempt_commits": cnt("engine.preempt_commits"),
            "prio_preempt_rollbacks": cnt("engine.preempt_rollbacks"),
            "prio_victims_evicted": cnt("engine.victims_evicted"),
            "prio_budget_deferred": cnt("engine.preempt_budget_deferred"),
            "prio_preempt_scan_dispatches":
                cnt("engine.preempt_scan_dispatch"),
            "prio_preempt_latency_p50_ms":
                round(float(np.percentile(lats, 50)) * 1e3, 3)
                if n_commit else None,
            "prio_preempt_latency_p99_ms":
                round(float(np.percentile(lats, 99)) * 1e3, 3)
                if n_commit else None,
            "prio_victims_per_preemption":
                round(float(vics.mean()), 3) if n_commit else None,
            "prio_budget_window_peak": int(window_peak),
            "prio_budget_max_per_min": int(max_evictions_per_min),
            "prio_injected_evict_failures": int(
                api.injected_evict_failures),
            "prio_injected_evict_timeouts": int(
                api.injected_evict_timeouts),
            "prio_duplicate_binds": int(dup),
            "prio_double_evictions": len(over_evicted),
            "prio_ghost_discrepancies": len(ghosts),
            "prio_degraded_steps": int(agg["degraded_steps"]),
        }
    finally:
        features.DEFAULT_FEATURE_GATE.reset()


def measure_extender_latency(n_nodes: int, rounds: int = 20):
    """Real HTTP /filter + /prioritize latency against the TPU backend at
    n_nodes (the 5s extender budget of core/extender.go:36, measured on
    hardware instead of asserted structurally — r4 VERDICT weak #5).
    Returns (p50_ms, p99_ms)."""
    import http.client
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod

    _backend, srv = _build_extender(n_nodes)
    try:
        lat = []
        for i in range(rounds + 3):
            pod = make_pod(f"ext-{i}", cpu=100, memory=256 << 20)
            body = json.dumps({"Pod": serde.encode_pod(pod),
                               "NodeNames": None, "Nodes": None})
            t0 = _time.perf_counter()
            for verb in ("filter", "prioritize"):
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=30)
                conn.request("POST", f"/scheduler/{verb}", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200
                conn.close()
            if i >= 3:  # first calls pay snapshot build + compile
                lat.append(_time.perf_counter() - t0)
        lat.sort()
        return (lat[len(lat) // 2] * 1e3,
                lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3)
    finally:
        srv.stop()


def measure_mixed_affinity(n_nodes: int, n_pods: int, warmup: bool = True):
    """The ISSUE 3 headline scenario: the standard drain protocol over the
    `mixed_affinity` profile (>=15% required (anti-)affinity pods — hostname
    anti riding the wave path, zone affinity through the seeded strict
    tail, symmetry targets in the plain stream). Collects the wave-path
    observability counters so silent routing regressions (affinity quietly
    flushing the pipeline again, or quietly skipping the strict tail) are
    visible in the bench JSON, not only in tests."""
    from kubernetes_tpu.utils.trace import COUNTERS

    if warmup:
        run_once(n_nodes, n_pods, "mixed_affinity")
    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    COUNTERS.reset()
    try:
        totals, elapsed, sched = run_once(n_nodes, n_pods, "mixed_affinity")
    finally:
        gc.enable()
        gc.unfreeze()
    snap = COUNTERS.snapshot()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    bound = totals["bound"]
    c2b = sched.metrics.create_to_bound
    return {
        "mixed_pods_s": round(bound / elapsed, 1) if elapsed > 0 else 0.0,
        "mixed_elapsed_s": round(elapsed, 3),
        "mixed_bound": bound,
        "mixed_unschedulable": totals["unschedulable"],
        "mixed_fence_requeued": totals.get("fence_requeued", 0),
        # drain_ labeled like the headline columns: pre-loaded scenario,
        # one shared creation instant (ISSUE 7 satellite)
        "mixed_drain_p50_create_to_bound_ms":
            round(c2b.percentile(50) * 1e3, 3),
        "mixed_drain_p99_create_to_bound_ms":
            round(c2b.percentile(99) * 1e3, 3),
        # wave-path routing observability (ISSUE 3 satellite): how many
        # pods the wave pass could NOT absorb, and how many placements the
        # topology fence re-validated away
        "mixed_affinity_strict_tail": cnt("engine.affinity_strict_tail"),
        "mixed_affinity_fence_requeues":
            cnt("engine.affinity_fence_requeues"),
        "mixed_affinity_straggler_requeues":
            cnt("engine.affinity_straggler_requeues"),
        "mixed_wave_dispatch": cnt("engine.wave_dispatch"),
        "mixed_wave_tail_dispatch": cnt("engine.wave_tail_dispatch"),
        "mixed_wave_encode_build": cnt("engine.wave_encode_build"),
        # conflict-round tail observability (ISSUE 5): how many round-loop
        # dispatches the strict tail cost and how many sequential ROUNDS
        # ran inside them — the whole point is rounds << tail pods; a
        # regression back to per-pod depth shows up here, not only in
        # wall clock
        "mixed_tail_rounds": cnt("engine.tail_rounds"),
        "mixed_tail_round_dispatch": cnt("engine.tail_round_dispatch"),
    }


def measure_gang_mix(n_nodes: int, n_pods: int, warmup: bool = True):
    """ISSUE 5 gang scenario: the `gang_mix` profile (~20% of pods in
    8–64-member full-quorum gangs, rest the mixed-affinity stream)
    drained twice on the same box — once with gangs riding the pipelined
    wave path (the new default) and once in FLUSH mode
    (Scheduler.gang_pipeline=False: every gang-bearing chunk drains the
    pipeline into the classic synchronous round — the r07/r08 behavior,
    kept reachable as this A/B's baseline). Both runs use the same fixed
    chunk so the comparison isolates the routing, not the chunking.

    The default shape is 1k nodes / 6k pods, NOT the 5k/30k headline:
    with gangs interleaved into every chunk, flush mode runs the WHOLE
    mixed stream through the classic path — per-chunk AffinityData
    rebuilds plus the full-label-axis strict scan, the costs
    PROFILE_r08 measured at >3,500 s (timed out) on the headline shape.
    The baseline must finish for the ratio to be a measurement.
    Asserts the hard invariant: ZERO partially bound gangs in either
    mode."""
    import gc

    from kubernetes_tpu.engine.gang import GANG_NAME_ANNOTATION
    from kubernetes_tpu.utils.trace import COUNTERS

    chunk = int(os.environ.get("BENCH_GANG_CHUNK", "1024"))

    def drain(gang_pipeline: bool):
        api, sched = build(n_nodes, n_pods, "gang_mix")
        sched.gang_pipeline = gang_pipeline
        t0 = time.monotonic()
        totals = sched.run_until_drained(max_batch=chunk)
        elapsed = time.monotonic() - t0
        by_gang = {}
        for p in api.list("Pod")[0]:
            g = p.annotations.get(GANG_NAME_ANNOTATION)
            if g is not None:
                by_gang.setdefault(g, []).append(bool(p.node_name))
        partial = sum(1 for flags in by_gang.values()
                      if len(set(flags)) != 1)
        return totals, elapsed, partial

    if warmup:
        # warm BOTH modes: the flush baseline must not be charged for
        # cold XLA compiles the pipelined run already amortized
        drain(True)
        drain(False)
    gc.collect()
    gc.freeze()
    gc.disable()
    COUNTERS.reset()
    try:
        totals, elapsed, partial = drain(True)
        snap = COUNTERS.snapshot()
        _totals_f, elapsed_flush, partial_flush = drain(False)
    finally:
        gc.enable()
        gc.unfreeze()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    # the hard invariant, enforced loudly: a partially bound gang is a
    # broken atomicity contract, not a perf data point — refuse to report
    # numbers over it (same spirit as the lint gate; explicit raise, not
    # a bare assert, so python -O cannot silently drop the check)
    if partial or partial_flush:
        raise RuntimeError(f"partially bound gangs: pipelined={partial} "
                           f"flush={partial_flush}")
    return {
        "gangmix_pods_s": round(totals["bound"] / elapsed, 1)
        if elapsed > 0 else 0.0,
        "gangmix_elapsed_s": round(elapsed, 3),
        "gangmix_bound": totals["bound"],
        "gangmix_unschedulable": totals["unschedulable"],
        "gangmix_partial_gangs": partial + partial_flush,  # 0 by the
        # raise above — kept in the JSON so trajectory readers see the
        # invariant was measured, not assumed
        "gangmix_chunk": chunk,
        # the A/B this scenario exists for: same drain with every
        # gang-bearing chunk flushing the pipeline (the old routing)
        "gangmix_flush_elapsed_s": round(elapsed_flush, 3),
        "gangmix_speedup_vs_flush": round(elapsed_flush / elapsed, 2)
        if elapsed > 0 else 0.0,
        # routing observability (ISSUE 5): gangs dispatched wave-granular,
        # gangs atomically rolled back at the fence, fence requeues
        "gangmix_gang_wave_dispatch": cnt("engine.gang_wave_dispatch"),
        "gangmix_gang_fence_rollbacks": cnt("engine.gang_fence_rollbacks"),
        "gangmix_gang_requeued": totals.get("gang_requeued", 0),
        "gangmix_fence_requeued": totals.get("fence_requeued", 0),
        "gangmix_wave_dispatch": cnt("engine.wave_dispatch"),
    }


# ------------------------------------------------------------ scale sweep
# ISSUE 12: the node axis as a SCALING dimension — the same drain at
# 5k/20k/50k nodes on 1 vs n forced host devices, placements asserted
# bit-identical across device counts, with the per-wave span and
# host-traffic counters proving the winner reduce moves O(n_devices)
# candidates and the delta path writes one shard per touched node. Each
# point runs in a SUBPROCESS because the forced-host device count must be
# fixed before any JAX initialization (same discipline as
# __graft_entry__.dryrun_multichip).


def _scale_env(n_devices: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # persistent compile cache, same reason as dryrun_multichip's env
    # builder: the sweep pays 6 drain + 2 stream subprocesses, and a warm
    # cache turns each point's XLA compiles from minutes into seconds
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".jax_cache"))
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "--xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags
        + f" --xla_force_host_platform_device_count={max(n_devices, 1)}"
    ).strip()
    return env


def _scale_sub(call: str, n_devices: int, timeout: float = 2400):
    import subprocess
    import sys
    here = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run(
        [sys.executable, "-c", f"import bench; bench.{call}"],
        cwd=here, env=_scale_env(n_devices), capture_output=True,
        text=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"scale-sweep subprocess failed rc={proc.returncode}:\n"
            + proc.stderr[-4000:])
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    return json.loads(lines[-1])


def _scale_drain_impl(n_nodes: int, n_pods: int, n_devices: int,
                      chunk: int = 4096, profile: str = "density") -> None:
    """One sweep point: an ENGINE-level pipelined drain (dispatch_waves /
    harvest_waves two deep — the Scheduler's drain body without the
    apiserver, so the measurement is the tensor pipeline, not 300k watch
    events), printed as one JSON line. Runs a one-chunk warmup drain on a
    throwaway cache first so XLA compiles are not charged to the wall."""
    import hashlib
    import resource
    import sys

    import numpy as np

    from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.utils.trace import COUNTERS

    mesh = None
    if n_devices > 1:
        from kubernetes_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(n_devices)

    def drain(nn, pods_n):
        cache = SchedulerCache()
        for nd in hollow_nodes(nn):
            cache.add_node(nd)
        engine = SchedulingEngine(cache, mesh=mesh)
        engine.track_dirty = True  # sole cache owner: hinted refresh
        engine.wave_pad_floor = chunk
        pending = PROFILES[profile](pods_n)
        bound = {}
        unsched = 0
        spans = []
        prev = None
        t0 = time.perf_counter()
        while pending or prev is not None:
            chunk_pods = pending[:chunk]
            del pending[:chunk]
            handle = engine.dispatch_waves(chunk_pods) if chunk_pods \
                else None
            if handle is None and chunk_pods:
                raise RuntimeError("scale profile fell off the wave path")
            if prev is not None:
                h = engine.harvest_waves(prev)
                for p in h.bound:
                    bound[p.name] = p.node_name
                unsched += len(h.unschedulable)
                pending.extend(h.conflicts)
                spans.append(h.t_block)
            prev = handle
        wall = time.perf_counter() - t0
        return bound, unsched, spans, wall

    t_setup0 = time.perf_counter()
    # compile warmup at the SAME node count (the wave program specializes
    # on N): a throwaway one-chunk drain pays every XLA compile so the
    # measured wall below is steady-state engine time only
    drain(n_nodes, chunk)  # warmup: compiles only, result discarded
    t_warm = time.perf_counter() - t_setup0
    COUNTERS.reset()
    bound, unsched, spans, wall = drain(n_nodes, n_pods)
    snap = COUNTERS.snapshot()

    def cnt(name):
        return int(snap.get(name, (0, 0.0))[0])

    digest = hashlib.sha256()
    for k in sorted(bound):
        digest.update(f"{k}:{bound[k]}\n".encode())
    spans_s = sorted(spans)
    out = {
        "n_nodes": n_nodes, "n_pods": n_pods, "n_devices": n_devices,
        "chunk": chunk, "profile": profile,
        "bound": len(bound), "unschedulable": unsched,
        "wall_s": round(wall, 3),
        "pods_per_s": round(len(bound) / wall, 1) if wall > 0 else 0.0,
        "warm_compile_s": round(t_warm, 1),
        "waves": len(spans),
        "wave_block_p50_ms": round(
            spans_s[len(spans_s) // 2] * 1e3, 2) if spans_s else None,
        "wave_block_max_ms": round(spans_s[-1] * 1e3, 2)
        if spans_s else None,
        # traffic proofs: the harvest fetch is O(P) per wave whatever N
        # is; the sharded winner reduce moves D*C candidate rows per
        # INNER wave iteration (the counter scales by waves_used, so the
        # per-dispatch figure = D * c_pad * inner waves — N never enters
        # it); the delta path ships only touched rows' shards
        "host_fetch_bytes": cnt("engine.host_fetch_bytes"),
        "host_fetch_bytes_per_wave": round(
            cnt("engine.host_fetch_bytes") / max(len(spans), 1)),
        "reduce_candidate_rows": cnt("engine.reduce_candidate_rows"),
        "reduce_candidate_rows_per_dispatch": round(
            cnt("engine.reduce_candidate_rows")
            / max(cnt("engine.wave_dispatch"), 1), 1),
        "shard_delta_rows": cnt("engine.shard_delta_rows"),
        "shard_upload_bytes": cnt("engine.shard_upload_bytes"),
        "device_upload_arrays": cnt("engine.device_upload_arrays"),
        "assume_delta_rows": cnt("snapshot.assume_delta_rows"),
        "encode_builds": cnt("engine.wave_encode_build"),
        "placements_sha256": digest.hexdigest(),
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024),
    }
    sys.stdout.write(json.dumps(out) + "\n")


def _scale_stream_impl(n_nodes: int, n_devices: int, rate: float,
                       duration_s: float, budget_ms: float) -> None:
    """The streaming leg at scale: run_arrival on a mesh-resident
    scheduler (n_devices > 1) or the unsharded engine, one JSON line.
    The delta-only invariant counters travel with the latency numbers."""
    import sys

    from kubernetes_tpu.utils.trace import COUNTERS

    COUNTERS.reset()
    res = run_arrival(n_nodes, rate=rate, duration_s=duration_s,
                      profile="density", budget_ms=budget_ms, warm=True,
                      mesh_devices=n_devices)
    snap = COUNTERS.snapshot()
    # the invariant counters diff against run_arrival's offer-window
    # baseline: warmup drains + the measured scheduler's one-time boot
    # encoding all land BEFORE it, so "encode_builds_during_run" == 0 IS
    # the delta-only acceptance read (a pre-warm reset could never show
    # it — warmup's own builds would always pollute the number)
    base = res.get("counters_at_offer_start", {})

    def window(name):
        return int(snap.get(name, (0, 0))[0]) - int(base.get(name, 0))

    res = dict(res)
    res["n_devices"] = n_devices
    res["shard_delta_rows"] = window("engine.shard_delta_rows")
    res["shard_upload_bytes"] = window("engine.shard_upload_bytes")
    res["encode_builds_during_run"] = window("engine.wave_encode_build")
    keep = ("offered_pods_s", "sustained_pods_s", "p50_ms", "p99_ms",
            "bound", "unbound", "backlog_at_offer_end", "budget_ms",
            "creator_jitter_ok", "n_devices", "shard_delta_rows",
            "shard_upload_bytes", "encode_builds_during_run",
            "quantum_peak")
    sys.stdout.write(json.dumps({k: res.get(k) for k in keep}) + "\n")


def measure_scale_sweep(shapes=((5_000, 30_000), (20_000, 120_000),
                                (50_000, 300_000)),
                        devices=(1, 8), chunk: int = 4096,
                        stream_nodes: int = 50_000,
                        stream_rate: float = 0.0,
                        stream_budget_ms: float = 0.0):
    """The ISSUE 12 acceptance scenario: the same hollow drain swept over
    cluster size x device count, placements asserted BIT-IDENTICAL across
    device counts at every shape (the sharded engine must be a pure
    layout choice), multi-vs-single device wall clocks reported side by
    side, plus the 50k-node streaming-arrival leg with a budget scaled to
    the cluster (the 250 ms headline budget is a 5k-node contract; the
    10x cluster gets a proportionally scaled bound, reported as its own
    budget_ms).

    Env knobs: BENCH_SCALE_SHAPES ("5000:30000,20000:120000,..."),
    BENCH_SCALE_DEVICES ("1,8"), BENCH_SCALE_CHUNK, BENCH_SCALE_STREAM=0
    to skip the arrival leg, BENCH_SCALE_STREAM_RATE/_BUDGET_MS."""
    env_shapes = os.environ.get("BENCH_SCALE_SHAPES", "")
    if env_shapes:
        shapes = tuple(tuple(int(x) for x in s.split(":"))
                       for s in env_shapes.split(",") if s)
    env_dev = os.environ.get("BENCH_SCALE_DEVICES", "")
    if env_dev:
        devices = tuple(int(d) for d in env_dev.split(","))
    chunk = int(os.environ.get("BENCH_SCALE_CHUNK", chunk))
    out = {"shapes": [], "chunk": chunk}
    ok_identical = True
    for (nn, pods_n) in shapes:
        row = {"n_nodes": nn, "n_pods": pods_n, "devices": {}}
        hashes = {}
        for d in devices:
            res = _scale_sub(
                f"_scale_drain_impl({nn}, {pods_n}, {d}, chunk={chunk})",
                d)
            row["devices"][str(d)] = res
            hashes[d] = res["placements_sha256"]
        if len(set(hashes.values())) > 1:
            ok_identical = False
            row["sharded_equals_unsharded"] = False
        else:
            row["sharded_equals_unsharded"] = True
        base = row["devices"].get("1")
        best = min((r for k, r in row["devices"].items() if k != "1"),
                   key=lambda r: r["wall_s"], default=None)
        if base and best:
            row["multi_vs_single_speedup"] = round(
                base["wall_s"] / best["wall_s"], 3)
            row["multi_beats_single"] = best["wall_s"] < base["wall_s"]
        out["shapes"].append(row)
    out["sharded_equals_unsharded_all"] = ok_identical
    if os.environ.get("BENCH_SCALE_STREAM", "1") != "0":
        # budget scaling: the 250ms budget was set against 5k nodes; a
        # 10x node axis gets a 10x-scaled latency bound and an offered
        # rate the 2-core box can honestly create against
        rate = stream_rate or float(
            os.environ.get("BENCH_SCALE_STREAM_RATE", 2000))
        budget = stream_budget_ms or float(
            os.environ.get("BENCH_SCALE_STREAM_BUDGET_MS",
                           250.0 * stream_nodes / 5000.0))
        dur = max(3.0, min(6.0, 12_000 / rate))
        stream = {"n_nodes": stream_nodes, "rate": rate,
                  "budget_ms": budget}
        for d in sorted({1, max(devices)}):
            try:
                stream[f"devices_{d}"] = _scale_sub(
                    f"_scale_stream_impl({stream_nodes}, {d}, {rate}, "
                    f"{dur}, {budget})", d)
            except Exception as e:
                stream[f"devices_{d}"] = {"error": str(e)[-500:]}
        out["stream_50k"] = stream
    return out


def lint_gate_or_die():
    """`--lint-gate` / BENCH_LINT_GATE=1: refuse to report perf numbers
    from a tree carrying unsuppressed graftlint hazards. A number measured
    over an aliasing upload or a hidden host sync is not a number — it is
    either racing (wrong placements under load) or quietly serialized
    (wrong overlap). Pure AST, milliseconds, no device."""
    import sys

    from kubernetes_tpu.analysis.lint import lint_gate
    ok, report = lint_gate()
    if not ok:
        print(report, file=sys.stderr)
        print(json.dumps({"metric": "schedule_pods_per_sec", "value": 0,
                          "unit": "pods/s", "error": "lint-gate: tree has "
                          "unsuppressed graftlint findings"}))
        raise SystemExit(3)


def main():
    import sys
    if "--trend" in sys.argv[1:]:
        # trajectory reader (ISSUE 15): no drain, no device — render the
        # BENCH_r*.json trend and exit nonzero on a regression past the
        # box-noise band (the CI contract; observability/trend.py)
        from kubernetes_tpu.observability.trend import main as trend_main
        raise SystemExit(trend_main(
            [a for a in sys.argv[1:] if a != "--trend"]))
    if "--lint-gate" in sys.argv[1:] \
            or os.environ.get("BENCH_LINT_GATE", "0") == "1":
        lint_gate_or_die()
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")
    warmup = os.environ.get("BENCH_WARMUP", "1") != "0"

    def attempt():
        # warmup (compile at identical shapes) INSIDE the retry scope: a
        # transient remote-compile failure during warmup must not zero the
        # round (it did in r02)
        if warmup:
            run_once(n_nodes, n_pods, profile)
        # quiesce the collector for the measured run: a gen-2 GC pass over a
        # heap holding 30k pods + 5k nodes costs 200-400ms of pure pause —
        # the standard CPython service tuning (freeze the warm heap, collect
        # nothing during the burst, restore after)
        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            return run_once(n_nodes, n_pods, profile)
        finally:
            gc.enable()
            gc.unfreeze()

    try:
        totals, elapsed, sched = attempt()
    except Exception as e:  # tunneled-TPU transport flakes are transient;
        # one retry so a single dropped RPC doesn't zero the round's number
        import sys
        print(f"bench: retrying after transient error: {e}", file=sys.stderr)
        totals, elapsed, sched = attempt()

    # extender wire latency on the same hardware (skippable for quick
    # local smokes; the driver's run keeps it on)
    ext_p50 = ext_p99 = None
    if os.environ.get("BENCH_EXTENDER", "1") != "0":
        try:
            ext_p50, ext_p99 = measure_extender_latency(n_nodes)
        except Exception as e:
            import sys
            print(f"bench: extender measurement failed: {e}",
                  file=sys.stderr)

    # compat-mode scheduleOne-over-HTTP throughput (the reference protocol
    # driven end to end; BENCH_COMPAT=0 to skip)
    compat = None
    if os.environ.get("BENCH_COMPAT", "1") != "0":
        try:
            compat = measure_compat_scheduleone(
                n_nodes,
                n_pods=int(os.environ.get("BENCH_COMPAT_PODS", 2000)),
                drivers=int(os.environ.get("BENCH_COMPAT_DRIVERS", 8)))
        except Exception as e:
            import sys
            print(f"bench: compat measurement failed: {e}", file=sys.stderr)

    # arrival-stream scenario — THE headline since ISSUE 7: rate-driven
    # creates against the always-on loop, per-interval bound/backlog
    # series, honest creator-stamped create->bound percentiles
    # (BENCH_ARRIVAL=0 to skip). Default offered rate is the ROADMAP
    # target: 20k pods/s with p99 create->bound under the 250ms budget.
    arrival = None
    sweeps = None
    saturation = None
    arrival_profile = profile if profile in ("density", "binpack") \
        else "density"
    arrival_rate = float(os.environ.get("BENCH_ARRIVAL_RATE", 20000))
    arrival_budget = float(os.environ.get("BENCH_ARRIVAL_BUDGET_MS", 250))
    arrival_secs = os.environ.get("BENCH_ARRIVAL_SECONDS", "")
    arrival_duration = float(arrival_secs) if arrival_secs \
        else max(1.5, min(6.0, 60_000 / arrival_rate))
    if os.environ.get("BENCH_ARRIVAL", "1") != "0":
        try:
            arrival = run_arrival(
                n_nodes, rate=arrival_rate, duration_s=arrival_duration,
                profile=arrival_profile, budget_ms=arrival_budget,
                max_burst=int(os.environ.get("BENCH_ARRIVAL_BURST", 0)),
                warm=warmup)
        except Exception as e:
            import sys
            print(f"bench: arrival measurement failed: {e}", file=sys.stderr)

    # recorder on/off A/B (ISSUE 13): the SAME arrival headline re-run
    # with the flight recorder armed, INTERLEAVED on/off trials on the
    # same box with per-arm medians — the telemetry overhead is
    # measured, not asserted (acceptance: <= 2% sustained-throughput
    # overhead), and a single-pair A/B cannot resolve 2% through this
    # box's documented +-30% run-to-run swing (the r13 lesson: one bad
    # leg reads as a fake regression). The headline run above is the
    # first off-arm trial. BENCH_RECORDER_AB=0 to skip,
    # BENCH_RECORDER_AB_TRIALS sets trials per arm (default 2).
    recorder_ab = None
    if arrival is not None \
            and os.environ.get("BENCH_RECORDER_AB", "1") != "0":
        import statistics
        trials = max(int(os.environ.get("BENCH_RECORDER_AB_TRIALS", "2")),
                     1)
        offs = [arrival["sustained_pods_s"]]
        ons, on_p99s = [], []
        rec_events = rec_dropped = None
        try:
            def _leg(rec_on):
                return run_arrival(
                    n_nodes, rate=arrival_rate,
                    duration_s=arrival_duration, profile=arrival_profile,
                    budget_ms=arrival_budget,
                    max_burst=int(os.environ.get("BENCH_ARRIVAL_BURST",
                                                 0)),
                    warm=warmup, recorder=rec_on)

            for _i in range(trials):
                r_on = _leg(True)
                ons.append(r_on["sustained_pods_s"])
                if r_on["p99_ms"] is not None:
                    on_p99s.append(r_on["p99_ms"])
                rec_events = r_on.get("recorder_events")
                rec_dropped = r_on.get("recorder_dropped")
                if len(offs) < trials:
                    offs.append(_leg(False)["sustained_pods_s"])
            # auto-escalation (ISSUE 20 satellite): when the two arms'
            # trial RANGES overlap, the pair cannot attribute the delta
            # to the recorder at all — escalate to the r17 6-trial
            # protocol (3 interleaved per arm) instead of shipping a
            # number the box noise wrote. r20's 4.8% "overhead" from 2
            # overlapping trials was exactly this failure.
            escalated = False
            while _ab_ranges_overlap(offs, ons) and len(ons) < 3:
                escalated = True
                r_on = _leg(True)
                ons.append(r_on["sustained_pods_s"])
                if r_on["p99_ms"] is not None:
                    on_p99s.append(r_on["p99_ms"])
                offs.append(_leg(False)["sustained_pods_s"])
            off_s = statistics.median(offs)
            on_s = statistics.median(ons)
            recorder_ab = {
                "recorder_ab_trials_per_arm": [len(offs), len(ons)],
                "recorder_ab_escalated": escalated,
                "recorder_ab_ranges_overlap":
                    _ab_ranges_overlap(offs, ons),
                "recorder_off_sustained_pods_s": round(off_s, 1),
                "recorder_on_sustained_pods_s": round(on_s, 1),
                "recorder_off_trials": offs,
                "recorder_on_trials": ons,
                "recorder_on_p99_ms": round(statistics.median(on_p99s), 3)
                if on_p99s else None,
                "recorder_events": rec_events,
                "recorder_dropped": rec_dropped,
                # positive = the recorder cost throughput; negative =
                # box noise favored the on arm (both travel — medians
                # over interleaved trials, never a cherry-pick)
                "telemetry_overhead_pct": round(
                    (off_s - on_s) / off_s * 100.0, 2) if off_s else None,
            }
        except Exception as e:
            import sys
            print(f"bench: recorder A/B failed: {e}", file=sys.stderr)

    # podtrace+SLO on/off A/B (ISSUE 15): the arrival headline re-run
    # with the pod-level black box armed at the DEFAULT sample rate —
    # same interleaved-medians methodology as the recorder A/B (a 2%
    # bar cannot be resolved by one pair on a ±30% box). The ON arm's
    # result carries the tail-forensics demo into the artifact.
    # BENCH_PODTRACE_AB=0 to skip, BENCH_PODTRACE_AB_TRIALS per arm.
    podtrace_ab = None
    arrival_podtrace = None
    if arrival is not None \
            and os.environ.get("BENCH_PODTRACE_AB", "1") != "0":
        import statistics
        trials = max(int(os.environ.get("BENCH_PODTRACE_AB_TRIALS",
                                        "2")), 1)
        offs = [arrival["sustained_pods_s"]]
        ons, on_p99s = [], []
        try:
            def _pleg(trace_on):
                return run_arrival(
                    n_nodes, rate=arrival_rate,
                    duration_s=arrival_duration, profile=arrival_profile,
                    budget_ms=arrival_budget,
                    max_burst=int(os.environ.get("BENCH_ARRIVAL_BURST",
                                                 0)),
                    warm=warmup, podtrace=trace_on)

            for _i in range(trials):
                r_on = _pleg(True)
                ons.append(r_on["sustained_pods_s"])
                if r_on["p99_ms"] is not None:
                    on_p99s.append(r_on["p99_ms"])
                arrival_podtrace = r_on["podtrace"]
                if len(offs) < trials:
                    offs.append(_pleg(False)["sustained_pods_s"])
            # same escalation contract as the recorder A/B: overlapping
            # arm ranges -> the r17 6-trial protocol
            escalated = False
            while _ab_ranges_overlap(offs, ons) and len(ons) < 3:
                escalated = True
                r_on = _pleg(True)
                ons.append(r_on["sustained_pods_s"])
                if r_on["p99_ms"] is not None:
                    on_p99s.append(r_on["p99_ms"])
                arrival_podtrace = r_on["podtrace"]
                offs.append(_pleg(False)["sustained_pods_s"])
            off_s = statistics.median(offs)
            on_s = statistics.median(ons)
            exemplars = (arrival_podtrace or {}).get("tail_exemplars", [])
            podtrace_ab = {
                "podtrace_ab_trials_per_arm": [len(offs), len(ons)],
                "podtrace_ab_escalated": escalated,
                "podtrace_ab_ranges_overlap":
                    _ab_ranges_overlap(offs, ons),
                "podtrace_off_sustained_pods_s": round(off_s, 1),
                "podtrace_on_sustained_pods_s": round(on_s, 1),
                "podtrace_off_trials": offs,
                "podtrace_on_trials": ons,
                "podtrace_on_p99_ms": round(statistics.median(on_p99s),
                                            3) if on_p99s else None,
                "podtrace_sample_rate": (arrival_podtrace or {}).get(
                    "stats", {}).get("sample_rate"),
                "podtrace_overhead_pct": round(
                    (off_s - on_s) / off_s * 100.0, 2) if off_s else None,
                # acceptance: every exemplar's phase attribution
                # telescopes to its create->bound exactly
                "tail_attribution_exact_all": bool(exemplars) and all(
                    e["attribution_exact"] for e in exemplars),
            }
        except Exception as e:
            import sys
            print(f"bench: podtrace A/B failed: {e}", file=sys.stderr)

    # offered-rate sweep + saturation search (BENCH_ARRIVAL_SWEEP=""
    # disables the sweep, BENCH_ARRIVAL_SAT=0 the search)
    sweep_env = os.environ.get("BENCH_ARRIVAL_SWEEP",
                               "5000,10000,20000,30000")
    if os.environ.get("BENCH_ARRIVAL", "1") != "0" and sweep_env:
        try:
            sweeps = arrival_sweep(
                n_nodes, [float(r) for r in sweep_env.split(",")],
                budget_ms=arrival_budget, profile=arrival_profile)
        except Exception as e:
            import sys
            print(f"bench: arrival sweep failed: {e}", file=sys.stderr)
    if os.environ.get("BENCH_ARRIVAL", "1") != "0" \
            and os.environ.get("BENCH_ARRIVAL_SAT", "1") != "0":
        try:
            saturation = saturation_search(n_nodes,
                                           budget_ms=arrival_budget,
                                           profile=arrival_profile)
        except Exception as e:
            import sys
            print(f"bench: saturation search failed: {e}", file=sys.stderr)

    # churn scenario (ISSUE 8): the arrival stream under the seeded fault
    # schedule, reported as a ratio against the same-box quiet run
    # (BENCH_CHURN=0 to skip; BENCH_CHURN_RATE overrides the offered rate)
    churn = None
    if os.environ.get("BENCH_CHURN", "1") != "0":
        try:
            # the churn profile's wave path (6% anti classes) runs well
            # under the density ceiling — offer a rate the quiet run can
            # actually absorb so `sustained` measures engine capacity in
            # BOTH runs (offering 20k/s against a ~2k/s mixed ceiling
            # measures backlog growth, not the churn degradation)
            churn_rate = float(os.environ.get(
                "BENCH_CHURN_RATE", min(arrival_rate, 5000)))
            churn = measure_churn(
                n_nodes, rate=churn_rate,
                duration_s=max(4.0, min(10.0, 40_000 / churn_rate)),
                budget_ms=arrival_budget)
        except Exception as e:
            import sys
            print(f"bench: churn measurement failed: {e}", file=sys.stderr)

    # rolling-update scenario (ISSUE 18): deployment-shaped evict-and-
    # recreate waves under maxSurge/maxUnavailable riding a diurnal
    # background offered-rate curve — update completion time, replacement
    # p99 create->bound on the loaded stream, store-truth zero-ghost
    # audit (BENCH_ROLLING=0 to skip; BENCH_ROLLING_* knobs)
    rolling = None
    if os.environ.get("BENCH_ROLLING", "1") != "0":
        try:
            rolling = measure_rolling_update(
                n_nodes=int(os.environ.get("BENCH_ROLLING_NODES", 256)),
                replicas=int(
                    os.environ.get("BENCH_ROLLING_REPLICAS", 400)),
                max_surge=int(os.environ.get("BENCH_ROLLING_SURGE", 40)),
                max_unavailable=int(
                    os.environ.get("BENCH_ROLLING_UNAVAILABLE", 40)),
                bg_rate=float(
                    os.environ.get("BENCH_ROLLING_BG_RATE", 1500)),
                budget_ms=arrival_budget)
        except Exception as e:
            import sys
            print(f"bench: rolling-update measurement failed: {e}",
                  file=sys.stderr)

    # priority / preemption scenario (ISSUE 14): overcommitted cluster,
    # mixed Borg-style bands, wave-path atomic preemption under injected
    # eviction faults — hard-fails on any duplicate bind, double
    # eviction, ghost victim, or disruption-budget breach
    # (BENCH_PRIORITY=0 to skip; BENCH_PRIO_* knobs)
    priority_churn = None
    if os.environ.get("BENCH_PRIORITY", "1") != "0":
        try:
            priority_churn = measure_priority_churn(
                n_nodes=int(os.environ.get("BENCH_PRIO_NODES", 240)),
                rate=float(os.environ.get("BENCH_PRIO_RATE", 2000)),
                duration_s=float(
                    os.environ.get("BENCH_PRIO_SECONDS", 4.0)),
                budget_ms=arrival_budget,
                evict_fail_rate=float(
                    os.environ.get("BENCH_PRIO_EVICT_FAIL", 0.02)),
                evict_timeout_rate=float(
                    os.environ.get("BENCH_PRIO_EVICT_TIMEOUT", 0.01)),
                max_evictions_per_min=int(
                    os.environ.get("BENCH_PRIO_EVICT_PER_MIN", 6000)))
        except Exception as e:
            import sys
            print(f"bench: priority_churn measurement failed: {e}",
                  file=sys.stderr)

    # mixed-criticality fast lane (ISSUE 17): the Sparrow sub-10ms tier
    # beside the bulk waves — fast-tier p99, bulk sustained vs same-run
    # solo, outcome-counter partition, delta-free probe
    # (BENCH_FASTLANE=0 to skip; BENCH_FASTLANE_* knobs)
    fastlane_mixed = None
    if os.environ.get("BENCH_FASTLANE", "1") != "0":
        try:
            fastlane_mixed = measure_fastlane_mixed(
                n_nodes=int(os.environ.get("BENCH_FASTLANE_NODES", 256)),
                rate=float(os.environ.get("BENCH_FASTLANE_RATE", 2000)),
                fast_rate=float(
                    os.environ.get("BENCH_FASTLANE_FAST_RATE", 100)),
                duration_s=float(
                    os.environ.get("BENCH_FASTLANE_SECONDS", 3.0)),
                budget_ms=arrival_budget)
        except Exception as e:
            import sys
            print(f"bench: fastlane measurement failed: {e}",
                  file=sys.stderr)

    # multi-frontend fleet (ISSUE 9): N concurrent compat scheduleOne
    # loops on ONE sidecar over HTTP — coalesced dispatch, Omega fence,
    # exactly-once binds under injected faults, store-truth audited
    # (BENCH_MULTIFRONTEND=0 to skip; BENCH_MF_CLIENTS, BENCH_MF_NODES,
    # BENCH_MF_STALE_MS, BENCH_MF_PODS_PER_CLIENT knobs)
    multi_frontend = None
    mf_clients = tuple(int(c) for c in os.environ.get(
        "BENCH_MF_CLIENTS", "1,10,100").split(","))
    if os.environ.get("BENCH_MULTIFRONTEND", "1") != "0":
        try:
            multi_frontend = measure_multi_frontend(
                int(os.environ.get("BENCH_MF_NODES", n_nodes)),
                clients_list=mf_clients,
                stale_window_ms=float(
                    os.environ.get("BENCH_MF_STALE_MS", 25)))
        except Exception as e:
            import sys
            print(f"bench: multi-frontend measurement failed: {e}",
                  file=sys.stderr)

    # process fleet (ISSUE 16): M full scheduler PROCESSES over one
    # shared cell through the fenced wire — scaling vs process count on
    # disjoint pools, conflict rate vs pending-pool overlap
    # (BENCH_MULTIPROC=0 to skip; BENCH_MP_WORKERS, BENCH_MP_NODES,
    # BENCH_MP_PODS_PER_WORKER, BENCH_MP_OVERLAPS knobs)
    multiproc = None
    if os.environ.get("BENCH_MULTIPROC", "1") != "0":
        try:
            multiproc = measure_multiproc(
                n_nodes=int(os.environ.get("BENCH_MP_NODES", 64)),
                workers_list=tuple(int(w) for w in os.environ.get(
                    "BENCH_MP_WORKERS", "1,2").split(",")),
                pods_per_worker=int(os.environ.get(
                    "BENCH_MP_PODS_PER_WORKER", 96)),
                overlaps=tuple(float(o) for o in os.environ.get(
                    "BENCH_MP_OVERLAPS", "0.5").split(",") if o))
        except Exception as e:
            import sys
            print(f"bench: multiproc measurement failed: {e}",
                  file=sys.stderr)

    # federation tier (ISSUE 20): M cell processes (the r18 engine
    # unchanged behind the async binary wire) behind ONE front-door
    # router scoring the fused [C, M] cell-aggregate tensor, with a
    # mid-offer cell brownout draining through the spillover path and
    # the store-truth exactly-once audit hard-failing the scenario
    # (BENCH_FEDERATION=0 to skip; BENCH_FED_CELLS, BENCH_FED_NODES,
    # BENCH_FED_PODS, BENCH_FED_RATE knobs — rate 0 = auto 250*cpus)
    federation = None
    if os.environ.get("BENCH_FEDERATION", "1") != "0":
        try:
            federation = measure_federation(
                n_cells=int(os.environ.get("BENCH_FED_CELLS", 4)),
                nodes_per_cell=int(os.environ.get("BENCH_FED_NODES",
                                                  50_000)),
                n_pods=int(os.environ.get("BENCH_FED_PODS", 1600)),
                rate=float(os.environ.get("BENCH_FED_RATE", 0)))
        except Exception as e:
            import sys
            print(f"bench: federation measurement failed: {e}",
                  file=sys.stderr)

    # wire-wall calibration (ISSUE 11 satellite): the NO-OP transport
    # floors on THIS box — threaded HTTP vs async binary — so every
    # fleet number above ships with its platform wall attribution
    # (BENCH_WIRE_FLOOR=0 to skip; BENCH_WIRE_FLOOR_CLIENTS knob)
    wire_floor = None
    if os.environ.get("BENCH_WIRE_FLOOR", "1") != "0":
        try:
            wire_floor = measure_wire_floor(
                n_clients=int(os.environ.get("BENCH_WIRE_FLOOR_CLIENTS",
                                             100)))
        except Exception as e:
            import sys
            print(f"bench: wire-floor measurement failed: {e}",
                  file=sys.stderr)

    # scale sweep (ISSUE 12): 5k/20k/50k nodes x 1-vs-8 forced host
    # devices, engine-level drain A/B with bit-identity + traffic
    # counters, plus the 50k streaming leg (BENCH_SCALE_SWEEP=0 to skip;
    # BENCH_SCALE_SHAPES/BENCH_SCALE_DEVICES/BENCH_SCALE_CHUNK/
    # BENCH_SCALE_STREAM* knobs)
    scale_sweep = None
    if os.environ.get("BENCH_SCALE_SWEEP", "1") != "0":
        try:
            scale_sweep = measure_scale_sweep()
        except Exception as e:
            import sys
            print(f"bench: scale sweep failed: {e}", file=sys.stderr)

    # mixed-affinity drain (ISSUE 3 headline): same box, same protocol,
    # >=15% required (anti-)affinity pods (BENCH_MIXED=0 to skip)
    mixed = None
    if os.environ.get("BENCH_MIXED", "1") != "0":
        try:
            mixed = measure_mixed_affinity(
                n_nodes, int(os.environ.get("BENCH_MIXED_PODS", n_pods)),
                warmup=warmup)
        except Exception as e:
            import sys
            print(f"bench: mixed-affinity measurement failed: {e}",
                  file=sys.stderr)

    # gang-heavy drain (ISSUE 5): gangs on the pipeline vs the
    # flush-every-gang baseline, same box, same chunk (BENCH_GANGMIX=0 to
    # skip)
    gangmix = None
    if os.environ.get("BENCH_GANGMIX", "1") != "0":
        try:
            gangmix = measure_gang_mix(
                int(os.environ.get("BENCH_GANGMIX_NODES", 1000)),
                int(os.environ.get("BENCH_GANGMIX_PODS", 6000)),
                warmup=warmup)
        except Exception as e:
            import sys
            print(f"bench: gang-mix measurement failed: {e}",
                  file=sys.stderr)

    bound = totals["bound"]
    pods_per_s = bound / elapsed if elapsed > 0 else 0.0
    c2b = sched.metrics.create_to_bound  # honest per-pod distribution:
    # first-seen-unscheduled -> bind-complete, queue wait included
    out = dict({
        "metric": f"pods scheduled/sec ({profile}, {n_nodes} nodes, {n_pods} pods, create->bound)",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_s / 100.0, 2),
        "elapsed_s": round(elapsed, 3),
        "bound": bound,
        "unschedulable": totals["unschedulable"],
        # drain_ prefix (ISSUE 7 satellite): the pre-loaded drain stamps
        # every pod at ONE List instant, so "create->bound" here measures
        # drain position, not scheduling latency (r09's p50 == p99 ==
        # 1010ms degenerate columns) — labeled explicitly so it can't be
        # compared against the arrival stream's honest per-pod numbers
        "drain_p50_create_to_bound_ms": round(c2b.percentile(50) * 1e3, 3),
        "drain_p99_create_to_bound_ms": round(c2b.percentile(99) * 1e3, 3),
        # pop -> bind-complete span per pod (scheduler.go:289 semantics)
        "p99_e2e_ms": round(sched.metrics.e2e_latency.percentile(99) * 1e3, 3),
        # HTTP /filter+/prioritize round at n_nodes vs the 5s extender
        # budget (core/extender.go:36), measured on this hardware
        "extender_p50_ms": round(ext_p50, 3) if ext_p50 is not None else None,
        "extender_p99_ms": round(ext_p99, 3) if ext_p99 is not None else None,
        # compat mode: scheduleOne loops over real HTTP (filter with full
        # NodeNames, prioritize over survivors, bind) — sustained pods/s
        # through the reference's own protocol
        "compat_pods_s": round(compat[0], 1) if compat else None,
        "compat_p50_ms": round(compat[1], 3) if compat and compat[1] else None,
        "compat_p99_ms": round(compat[2], 3) if compat and compat[2] else None,
        "compat_bound": compat[3] if compat else None,
        "compat_unschedulable": compat[4] if compat else None,
        # arrival stream (the ISSUE 7 headline): always-on loop, offered
        # vs sustained PER INTERVAL with the backlog series alongside —
        # sustained is computed over the offer window only, so collapse
        # cannot hide in the post-offer drain; create->bound percentiles
        # are creator-stamped per pod
        "arrival_offered_pods_s": arrival["offered_pods_s"]
        if arrival else None,
        "arrival_sustained_pods_s": arrival["sustained_pods_s"]
        if arrival else None,
        "arrival_backlog_at_offer_end": arrival["backlog_at_offer_end"]
        if arrival else None,
        "arrival_unbound": arrival["unbound"] if arrival else None,
        "arrival_interval_s": arrival["interval_s"] if arrival else None,
        "arrival_intervals": arrival["intervals"] if arrival else None,
        "arrival_backlog_series": arrival["backlog_series"]
        if arrival else None,
        "arrival_offered_series": arrival["offered_series"]
        if arrival else None,
        "arrival_p50_create_to_bound_ms": round(arrival["p50_ms"], 3)
        if arrival and arrival["p50_ms"] is not None else None,
        "arrival_p99_create_to_bound_ms": round(arrival["p99_ms"], 3)
        if arrival and arrival["p99_ms"] is not None else None,
        "arrival_bound": arrival["bound"] if arrival else None,
        "arrival_budget_ms": arrival["budget_ms"] if arrival else None,
        "arrival_quantum_peak": arrival["quantum_peak"]
        if arrival else None,
        # creator self-audit (ISSUE 7 satellite): a high-rate run whose
        # creator lagged or burst past its bound measured the creator,
        # not the scheduler — the flag travels with the numbers
        "arrival_creator_max_burst": arrival["creator_max_burst"]
        if arrival else None,
        "arrival_creator_lag_p99_ms": arrival["creator_lag_p99_ms"]
        if arrival else None,
        "arrival_creator_jitter_ok": arrival["creator_jitter_ok"]
        if arrival else None,
        # robustness telemetry (ISSUE 8): bind errors + fence/degrade
        # counters travel with the headline arrival numbers
        "arrival_bind_errors": arrival["bind_errors"] if arrival else None,
        "arrival_fence_requeued": arrival["fence_requeued"]
        if arrival else None,
        "arrival_liveness_requeued": arrival["liveness_requeued"]
        if arrival else None,
        "arrival_degraded_steps": arrival["degraded_steps"]
        if arrival else None,
        # recorder on/off A/B (ISSUE 13): telemetry overhead measured on
        # the same box, back-to-back with the headline
        "arrival_recorder_ab": recorder_ab,
        "telemetry_overhead_pct": recorder_ab["telemetry_overhead_pct"]
        if recorder_ab else None,
        # pod-level black box (ISSUE 15): sampled-tracing overhead A/B +
        # the tail-forensics demo (slowest-K exemplar timelines with
        # exact per-phase attribution) and the SLO engine's view of the
        # measured window
        "arrival_podtrace_ab": podtrace_ab,
        "podtrace_overhead_pct": podtrace_ab["podtrace_overhead_pct"]
        if podtrace_ab else None,
        "arrival_podtrace": arrival_podtrace,
        # offered sweeps + saturation search: the max offered rate the
        # engine sustains with p99 create->bound under the budget
        "arrival_sweeps": sweeps,
        "arrival_saturation": saturation,
        # multi-frontend fleet (ISSUE 9): aggregate scheduleOne throughput
        # per client count over the reference protocol + the fleet
        # extensions, fence conflict rate, shed rate, exactly-once audit
        # (store truth). `multi_frontend_pods_s` is the SERVICE capacity
        # (in-process fleet — coalescer/fence/ledger under 100 concurrent
        # frontends); `multi_frontend_wire_pods_s` is the same protocol
        # through Python http.server, whose ~200 req/s 100-thread platform
        # ceiling on this box caps it far below the service (a no-op
        # handler measures the same wall) — wire numbers read against
        # that, not against the engine.
        "multi_frontend": multi_frontend,
        "multi_frontend_pods_s": multi_frontend.get(
            "inproc", {}).get("pods_s") if multi_frontend else None,
        "multi_frontend_wire_pods_s": multi_frontend.get(
            "clients_100", multi_frontend.get(
                f"clients_{max(int(c) for c in mf_clients)}", {})).get(
                    "pods_s") if multi_frontend else None,
        "multi_frontend_vs_r09_compat": round(multi_frontend.get(
            "inproc", {}).get("pods_s", 0) / 19.0, 1)
        if multi_frontend
        and multi_frontend.get("inproc", {}).get("pods_s") else None,
        "multi_frontend_conflict_rate": multi_frontend.get(
            "tight", {}).get("conflict_rate") if multi_frontend else None,
        "multi_frontend_duplicate_binds": max(
            (r.get("duplicate_binds", 0)
             for r in multi_frontend.values()), default=0)
        if multi_frontend else None,
        # transport A/B (ISSUE 11): the same 100-frontend fleet over the
        # async binary wire vs threaded HTTP vs in-process, with the
        # no-op platform floors alongside — the acceptance ratios travel
        # in the artifact
        "wire_floor": wire_floor,
        "multi_frontend_binwire_pods_s": multi_frontend.get(
            "binwire_100", multi_frontend.get(
                f"binwire_{max(int(c) for c in mf_clients)}", {})).get(
                    "pods_s") if multi_frontend else None,
        "multi_frontend_embedded_pods_s": multi_frontend.get(
            "embedded", {}).get("pods_s") if multi_frontend else None,
        "binwire_vs_http_wire": _ratio(
            multi_frontend, "binwire_100", "clients_100")
        if multi_frontend else None,
        "binwire_vs_inproc": _ratio(multi_frontend, "binwire_100",
                                    "inproc")
        if multi_frontend else None,
        # process fleet (ISSUE 16): the multiproc_N scenarios — M full
        # scheduler processes racing one shared cell through the bind
        # fence. `multiproc_pods_s` is the max-M aggregate on DISJOINT
        # pools (the scaling headline the trend gate tracks from r18);
        # the overlap keys carry Omega's conflict economics; the store
        # audit (duplicate_binds) is the hard-zero acceptance bar.
        "multiproc": multiproc,
        "multiproc_pods_s": max(
            (v.get("pods_s", 0) for k, v in multiproc.items()
             if isinstance(v, dict) and k.startswith("multiproc_")
             and "overlap" not in k), default=None)
        if multiproc else None,
        "multiproc_scaling": multiproc.get("scaling_max_vs_1")
        if multiproc else None,
        "multiproc_duplicate_binds": multiproc.get("duplicate_binds_max")
        if multiproc else None,
        # scale sweep (ISSUE 12): node-axis scaling A/B — per-shape 1-vs-8
        # device walls, bit-identity verdicts, O(n_devices) reduce +
        # one-shard-per-node delta counters, 50k streaming leg
        "scale_sweep": scale_sweep,
        "scale_sharded_equals_unsharded": scale_sweep.get(
            "sharded_equals_unsharded_all") if scale_sweep else None,
        # Sparrow fast lane (ISSUE 17): the mixed-criticality headline
        # pair the trend gate tracks from r19 — fast-tier p99
        # create->bound and the bulk tier's sustained fraction of its
        # same-run solo rate
        "fastlane_mixed": fastlane_mixed,
        "fastlane_p99_ms": fastlane_mixed.get("fastlane_p99_ms")
        if fastlane_mixed else None,
        "mixed_bulk_sustained": fastlane_mixed.get("mixed_bulk_sustained")
        if fastlane_mixed else None,
        "fastlane_duplicate_binds": fastlane_mixed.get(
            "fastlane_duplicate_binds") if fastlane_mixed else None,
        # federation tier (ISSUE 20): the trend-tracked headline trio —
        # aggregate nodes behind the front door, router admission p99 on
        # top of per-cell create->bound, and pods spilled-then-bound
        # under the brownout — plus the full scenario (cpus + scaled
        # offered rate disclosed inside)
        "federation": federation,
        "federation_agg_nodes": federation.get("agg_nodes")
        if federation else None,
        "federation_router_p99_ms": federation.get(
            "router_admission_p99_ms") if federation else None,
        "federation_spillover_bound": federation.get("spillover_bound")
        if federation else None,
        "federation_duplicate_binds": (
            federation.get("cross_cell_double_binds", 0)
            + max(federation.get("duplicate_binds_per_cell",
                                 {}).values(), default=0))
        if federation else None,
    }, **(churn or {}), **(rolling or {}), **(priority_churn or {}),
        **(mixed or {}), **(gangmix or {}))
    # box-shape disclosure (ISSUE 17 satellite): every scenario's JSON
    # carries the CPU count it ran on — the trend reader uses it to
    # separate code regressions from runner-shape changes (the r18
    # churn_vs_quiet 0.45 "dip" was a 2-core round read against 1-core)
    ncpu = os.cpu_count()
    out["cpus"] = ncpu
    for v in out.values():
        if isinstance(v, dict) and "cpus" not in v:
            v["cpus"] = ncpu
    print(json.dumps(out))

    # resume the bench trajectory: persist this round's numbers as the
    # CURRENT round's artifact — same {cmd, rc, parsed} shape as the
    # driver-written BENCH_r01..r05 files, so trajectory readers keep
    # working. BENCH_ARTIFACT= (empty) disables, or names another round;
    # the default is pinned to THIS round so a bench run can never
    # rewrite a prior round's file as commit noise (ISSUE 11 satellite).
    artifact = os.environ.get("BENCH_ARTIFACT", "BENCH_r21.json")
    if artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            artifact)
        try:
            with open(path, "w") as f:
                json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                           "parsed": out}, f, indent=2)
                f.write("\n")
        except OSError as e:
            import sys
            print(f"bench: artifact write failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
