"""Headline benchmark: batch-place the pending queue on a hollow cluster.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

Scenario (north star, BASELINE.md): 30,000 pending pods onto a 5,000-node
hollow cluster, end-to-end through the control plane — apiserver-lite create,
watch-driven queue fill, tensor snapshot, fused TPU wave placement through
the two-stage PIPELINED drain (wave k+1's device eval overlapping wave k's
columnar assume/bind/watch-drain — engine/scheduler.py), bulk bind writes,
watch confirmation.

vs_baseline is the ratio against the reference's 100 pods/s warn-level
scheduler throughput (test/integration/scheduler_perf/scheduler_test.go:35 —
the hard floor is 30 pods/s; real 1.7-era deployments sat between the two).

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_PROFILE (density|binpack|affinity|
hetero), BENCH_WARMUP=0 to skip the compile-warming run.
"""

from __future__ import annotations

import json
import os
import time

# persistent XLA compilation cache: a flaky remote-compile service mid-round
# costs one retry, not the round (r02 lost its number to a warmup-time
# connection refusal). Set before any jax import traces a kernel.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
try:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def build(n_nodes: int, n_pods: int, profile: str):
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + n_pods)))
    nodes = hollow_nodes(n_nodes, heterogeneous=(profile == "hetero"),
                         gpu_fraction=0.3 if profile == "hetero" else 0.0,
                         taint_fraction=0.1 if profile == "hetero" else 0.0)
    pods = PROFILES[profile](n_pods)
    load_cluster(api, nodes, pods)
    sched = Scheduler(api, record_events=False)
    sched.start()
    return api, sched


def run_once(n_nodes: int, n_pods: int, profile: str):
    api, sched = build(n_nodes, n_pods, profile)
    # pipeline knobs: BENCH_PIPELINE=0 -> classic synchronous rounds;
    # BENCH_OVERLAP=0 -> pipelined dataflow, sequential execution (the A/B
    # debug mode); BENCH_CHUNK=<n> -> fixed wave size (default: auto)
    pipeline = os.environ.get("BENCH_PIPELINE", "1") != "0"
    overlap = os.environ.get("BENCH_OVERLAP", "1") != "0"
    chunk = int(os.environ.get("BENCH_CHUNK", "0"))
    t0 = time.monotonic()
    totals = sched.run_until_drained(max_batch=chunk, pipeline=pipeline,
                                     overlap=overlap)
    elapsed = time.monotonic() - t0
    return totals, elapsed, sched


def _build_extender(n_nodes: int):
    """Sidecar backend + HTTP server over a hollow cluster, warmed so the
    first measured request never pays snapshot build + kernel compile."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )

    backend = TPUExtenderBackend()
    nodes = hollow_nodes(n_nodes)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 16}"
    backend.sync_nodes(nodes)
    backend.filter(make_pod("warm", cpu=100, memory=256 << 20), None, None)
    backend.prioritize(make_pod("warm2", cpu=100, memory=256 << 20),
                       None, None)
    srv = ExtenderHTTPServer(backend, prefix="/scheduler")
    srv.start()
    return backend, srv


def measure_compat_scheduleone(n_nodes: int, n_pods: int = 2000,
                               drivers: int = 8,
                               sync_interval_s: float = 1.0):
    """Compat-mode throughput: simulated scheduleOne loops driving the
    sidecar over REAL HTTP with the reference extender protocol
    (core/extender.go:100 Filter, :157 Prioritize, :199 Bind; wire structs
    api/types.go:158-204). Each driver is one scheduler's serial
    scheduleOne: POST /filter with the full candidate NodeNames list
    (nodeCacheCapable, extender.go:113-124), POST /prioritize with the
    survivors, pick the top score, POST /bind — so every bind is visible
    to every later evaluation, like a fleet of schedulers sharing one
    sidecar.

    Capacity feedback: the /bind wire carries only identifiers, so (as in
    the real deployment) the sidecar learns bound pods' RESOURCES from the
    periodic bulk cache sync — a housekeeping thread POSTs the full bound
    set to /cache/pods every `sync_interval_s` (the nodeCacheCapable
    snapshot-POST loop), so requested capacity accrues and scores move
    with load, and the measurement pays the re-sync invalidation cost too.
    Returns (pods_per_s, p50_ms, p99_ms, bound, unschedulable)."""
    import dataclasses
    import http.client
    import threading
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod

    backend, srv = _build_extender(n_nodes)
    node_names = list(backend.engine.snapshot.node_names)
    # the candidate list is invariant across the stream — serialize it once
    # per driver instead of per request (the scheduler equivalent: the
    # marshaled node-name set it would cache alongside its snapshot)
    names_json = json.dumps(node_names, separators=(",", ":"))
    lat_all = []
    bound = [0]
    unsched = [0]
    errors = []
    lock = threading.Lock()
    bound_specs = {}  # pod key -> encoded bound pod (for the bulk sync)
    done = threading.Event()
    per = (n_pods + drivers - 1) // drivers

    def syncer():
        # a dead syncer must FAIL the measurement like a dead driver does
        # (capacity feedback silently stopping would leave compat_pods_s
        # looking valid while no longer measuring what it claims); one
        # reconnect per failure, two consecutive failures abort
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        failures = 0
        while not done.wait(sync_interval_s):
            with lock:
                items = list(bound_specs.values())
            if not items:
                continue
            try:
                body = json.dumps({"items": items}, separators=(",", ":"))
                conn.request("POST", "/scheduler/cache/pods", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"cache sync HTTP {resp.status}")
                failures = 0
            except Exception as e:
                failures += 1
                try:
                    conn.close()
                except Exception:
                    pass
                if failures >= 2:
                    with lock:
                        errors.append(
                            f"syncer: {type(e).__name__}: {e}")
                    return
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=30)
        conn.close()

    def drive(d: int):
        try:
            _drive(d)
        except Exception as e:  # surface to the caller — a dead driver
            # thread must fail the measurement, not silently shrink it
            with lock:
                errors.append(f"driver {d}: {type(e).__name__}: {e}")

    def _drive(d: int):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def post_raw(path, body):
            conn.request("POST", f"/scheduler/{path}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = json.loads(resp.read())
            if resp.status != 200:  # explicit: bare assert vanishes
                # under python -O, silently corrupting the measurement
                raise RuntimeError(f"HTTP {resp.status} on {path}: {data}")
            return data

        lat = []
        n_bound = 0
        n_unsched = 0
        for i in range(per):
            if d * per + i >= n_pods:
                break
            pod = make_pod(f"compat-{d}-{i}", cpu=100, memory=256 << 20)
            enc = json.dumps(serde.encode_pod(pod), separators=(",", ":"))
            t0 = _time.perf_counter()
            out = post_raw(
                "filter",
                '{"Pod":' + enc + ',"NodeNames":' + names_json
                + ',"Nodes":null}')
            passed = out.get("NodeNames") or []
            if not passed:
                # counted, not silently dropped: an under-capacity run must
                # be visible in the result, like every other shrink path
                n_unsched += 1
                lat.append(_time.perf_counter() - t0)
                continue
            passed_json = names_json if len(passed) == len(node_names) \
                else json.dumps(passed, separators=(",", ":"))
            scores = post_raw(
                "prioritize",
                '{"Pod":' + enc + ',"NodeNames":' + passed_json
                + ',"Nodes":null}')
            host = max(scores, key=lambda e: e["Score"])["Host"]
            out = post_raw("bind", json.dumps(
                {"PodName": pod.name, "PodNamespace": pod.namespace,
                 "PodUID": pod.uid, "Node": host},
                separators=(",", ":")))
            if not out.get("Error"):
                n_bound += 1
                spec = serde.encode_pod(
                    dataclasses.replace(pod, node_name=host))
                with lock:
                    bound_specs[pod.key()] = spec
            lat.append(_time.perf_counter() - t0)
        conn.close()
        with lock:
            lat_all.extend(lat)
            bound[0] += n_bound
            unsched[0] += n_unsched

    threads = [threading.Thread(target=drive, args=(d,))
               for d in range(drivers)]
    sync_thread = None
    if sync_interval_s > 0:
        sync_thread = threading.Thread(target=syncer, daemon=True)
        sync_thread.start()
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    done.set()
    if sync_thread is not None:
        sync_thread.join(timeout=30)
    srv.stop()
    if errors:
        raise RuntimeError("; ".join(errors))
    lat_all.sort()
    if not lat_all or elapsed <= 0:
        return 0.0, None, None, 0, unsched[0]
    return (bound[0] / elapsed,
            lat_all[len(lat_all) // 2] * 1e3,
            lat_all[min(int(len(lat_all) * 0.99), len(lat_all) - 1)] * 1e3,
            bound[0], unsched[0])


def run_arrival(n_nodes: int, rate: float, duration_s: float,
                profile: str = "density", pipeline: bool = True):
    """Arrival-stream scenario (VERDICT r5 weak #3): pods are CREATED at a
    configured rate while the scheduler runs, instead of pre-loaded and
    drained once — the reference's density suite semantics
    (test/integration/scheduler_perf/scheduler_test.go:34-39 per-interval
    sustained throughput; test/e2e/scalability/density.go:316-320 startup
    latency under churn). The scheduler consumes through the two-stage
    pipelined drain (engine/scheduler.py _DrainPipeline) unless
    pipeline=False.

    Returns a dict: intervals (1s-bucket bound counts), offered_pods_s,
    sustained_pods_s, p50_ms/p99_ms (per-pod create->bound — MEANINGFUL:
    pods arriving in different rounds see different queue states, so
    p50 != p99), bound, backlog_at_offer_end (queue depth the instant the
    creator finished — the host-bound smoking gun a throughput number
    alone would hide), and unbound (pods never placed). Offered vs
    sustained vs backlog together make a host-bound run IMPOSSIBLE to
    misread as keeping up with the offered rate."""
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    total = int(rate * duration_s)
    api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + total)))
    nodes = hollow_nodes(n_nodes)
    load_cluster(api, nodes, [])
    pods = PROFILES[profile](total)
    sched = Scheduler(api, record_events=False)
    sched.start()
    import threading
    created = [0]
    bound_log = []  # (round start, round end, pods bound) rel. to t0
    t0 = time.monotonic()

    def creator():
        # offered-rate creator on its OWN thread: a schedule round that
        # outlives 1/rate must not stall arrivals, or the "rate-driven"
        # scenario silently degrades back into bursty pre-loaded batches
        # (the very shape this scenario replaces). ApiServerLite.create is
        # lock-protected, so this races the scheduler safely.
        while created[0] < total:
            now = time.monotonic() - t0
            due = min(total, int(rate * now))
            for p in pods[created[0]:due]:
                api.create("Pod", p)
            created[0] = due
            next_due = t0 + (created[0] + 1) / rate
            delay = next_due - time.monotonic()
            if delay > 0:
                time.sleep(min(delay, 0.01))

    creator_thread = threading.Thread(target=creator, daemon=True)
    creator_thread.start()
    # wall-clock safety net, NOT a round budget: a round-count backstop
    # silently truncates low-rate runs (empty rounds take microseconds),
    # returning a plausible-looking JSON over a partial window
    deadline = t0 + max(60.0, duration_s * 20)
    pipe = sched.pipeline() if pipeline else None
    backlog_at_offer_end = None
    try:
        while True:
            r0 = time.monotonic() - t0
            stats = pipe.step() if pipe is not None \
                else sched.schedule_round()
            r1 = time.monotonic() - t0
            if stats["bound"]:
                bound_log.append((r0, r1, stats["bound"]))
            if backlog_at_offer_end is None and created[0] >= total:
                # the offered stream just ended: whatever is still queued
                # or mid-pipeline (popped into the in-flight wave but not
                # yet harvested) is the backlog the scheduler could not
                # keep up with
                inflight = 0
                if pipe is not None and pipe.inflight is not None:
                    inflight = len(pipe.inflight.pods)
                backlog_at_offer_end = len(sched.queue) + inflight
            if created[0] >= total and stats["popped"] == 0 \
                    and (pipe is None or pipe.idle) \
                    and sched.sync() == 0 \
                    and sched.queue.ready_count() == 0 \
                    and not sched.queue._deferred:
                # the deferred (backoff) heap must drain too: a pod requeued
                # after a transient bind error is RETRIABLE, and abandoning
                # it would report percentiles over a silently partial
                # population. Truly-unschedulable pods never stop
                # re-entering the ready queue, so the wall-clock deadline
                # above still bounds the run.
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"arrival run incomplete after {deadline - t0:.0f}s: "
                    f"created {created[0]}/{total}, bound "
                    f"{sum(n for _, _, n in bound_log)}")
            if stats["popped"] == 0 and stats["bound"] == 0:
                time.sleep(0.005)  # idle: wait for arrivals, don't busy-spin
    finally:
        if pipe is not None:
            leftover = pipe.close()
            if leftover.get("bound"):
                bound_log.append((time.monotonic() - t0,
                                  time.monotonic() - t0,
                                  leftover["bound"]))
    creator_thread.join(timeout=10)
    # per-interval sustained throughput (1s buckets; scheduler_test.go:34-39
    # reports per-interval scheduled counts). A round's binds are spread
    # uniformly over the round's own duration — on a host where one batch
    # round outlives the bucket width, attributing the whole round to its
    # completion instant would show [0, 0, burst] instead of the real rate.
    # `sustained` is the median over the ACTIVE window (first..last bucket
    # with binds) so ramp-in zeros don't mask it.
    end = bound_log[-1][1] if bound_log else 0.0
    intervals = [0.0] * (int(end) + 1)
    for a, b, n in bound_log:
        span = max(b - a, 1e-9)
        for k in range(int(a), min(int(b), len(intervals) - 1) + 1):
            overlap = max(0.0, min(b, k + 1) - max(a, k))
            intervals[k] += n * overlap / span
    intervals = [round(v, 1) for v in intervals]
    nz = [i for i, n in enumerate(intervals) if n]
    if nz:
        active = intervals[nz[0]:nz[-1] + 1]
        # trim the LEADING ramp (warmup rounds bind a trickle before the
        # engine hits stride) — buckets under 25% of peak at the front
        # would otherwise dominate the median in short windows and report
        # the warmup rate as "sustained"
        peak = max(active)
        lead = 0
        while lead < len(active) - 1 and active[lead] < 0.25 * peak:
            lead += 1
        steady = active[lead:]
        sustained = sorted(steady)[len(steady) // 2]
    else:
        sustained = 0.0
    c2b = sched.metrics.create_to_bound
    bound = sum(n for _, _, n in bound_log)
    return {
        "intervals": intervals,
        "offered_pods_s": float(rate),
        "sustained_pods_s": float(sustained),
        "p50_ms": c2b.percentile(50) * 1e3,
        "p99_ms": c2b.percentile(99) * 1e3,
        "bound": int(round(bound)),
        "backlog_at_offer_end": int(backlog_at_offer_end or 0),
        "unbound": total - int(round(bound)),
    }


def measure_extender_latency(n_nodes: int, rounds: int = 20):
    """Real HTTP /filter + /prioritize latency against the TPU backend at
    n_nodes (the 5s extender budget of core/extender.go:36, measured on
    hardware instead of asserted structurally — r4 VERDICT weak #5).
    Returns (p50_ms, p99_ms)."""
    import http.client
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod

    _backend, srv = _build_extender(n_nodes)
    try:
        lat = []
        for i in range(rounds + 3):
            pod = make_pod(f"ext-{i}", cpu=100, memory=256 << 20)
            body = json.dumps({"Pod": serde.encode_pod(pod),
                               "NodeNames": None, "Nodes": None})
            t0 = _time.perf_counter()
            for verb in ("filter", "prioritize"):
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=30)
                conn.request("POST", f"/scheduler/{verb}", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200
                conn.close()
            if i >= 3:  # first calls pay snapshot build + compile
                lat.append(_time.perf_counter() - t0)
        lat.sort()
        return (lat[len(lat) // 2] * 1e3,
                lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3)
    finally:
        srv.stop()


def measure_mixed_affinity(n_nodes: int, n_pods: int, warmup: bool = True):
    """The ISSUE 3 headline scenario: the standard drain protocol over the
    `mixed_affinity` profile (>=15% required (anti-)affinity pods — hostname
    anti riding the wave path, zone affinity through the seeded strict
    tail, symmetry targets in the plain stream). Collects the wave-path
    observability counters so silent routing regressions (affinity quietly
    flushing the pipeline again, or quietly skipping the strict tail) are
    visible in the bench JSON, not only in tests."""
    from kubernetes_tpu.utils.trace import COUNTERS

    if warmup:
        run_once(n_nodes, n_pods, "mixed_affinity")
    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    COUNTERS.reset()
    try:
        totals, elapsed, sched = run_once(n_nodes, n_pods, "mixed_affinity")
    finally:
        gc.enable()
        gc.unfreeze()
    snap = COUNTERS.snapshot()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    bound = totals["bound"]
    c2b = sched.metrics.create_to_bound
    return {
        "mixed_pods_s": round(bound / elapsed, 1) if elapsed > 0 else 0.0,
        "mixed_elapsed_s": round(elapsed, 3),
        "mixed_bound": bound,
        "mixed_unschedulable": totals["unschedulable"],
        "mixed_fence_requeued": totals.get("fence_requeued", 0),
        "mixed_p50_create_to_bound_ms": round(c2b.percentile(50) * 1e3, 3),
        "mixed_p99_create_to_bound_ms": round(c2b.percentile(99) * 1e3, 3),
        # wave-path routing observability (ISSUE 3 satellite): how many
        # pods the wave pass could NOT absorb, and how many placements the
        # topology fence re-validated away
        "mixed_affinity_strict_tail": cnt("engine.affinity_strict_tail"),
        "mixed_affinity_fence_requeues":
            cnt("engine.affinity_fence_requeues"),
        "mixed_affinity_straggler_requeues":
            cnt("engine.affinity_straggler_requeues"),
        "mixed_wave_dispatch": cnt("engine.wave_dispatch"),
        "mixed_wave_tail_dispatch": cnt("engine.wave_tail_dispatch"),
        "mixed_wave_encode_build": cnt("engine.wave_encode_build"),
        # conflict-round tail observability (ISSUE 5): how many round-loop
        # dispatches the strict tail cost and how many sequential ROUNDS
        # ran inside them — the whole point is rounds << tail pods; a
        # regression back to per-pod depth shows up here, not only in
        # wall clock
        "mixed_tail_rounds": cnt("engine.tail_rounds"),
        "mixed_tail_round_dispatch": cnt("engine.tail_round_dispatch"),
    }


def measure_gang_mix(n_nodes: int, n_pods: int, warmup: bool = True):
    """ISSUE 5 gang scenario: the `gang_mix` profile (~20% of pods in
    8–64-member full-quorum gangs, rest the mixed-affinity stream)
    drained twice on the same box — once with gangs riding the pipelined
    wave path (the new default) and once in FLUSH mode
    (Scheduler.gang_pipeline=False: every gang-bearing chunk drains the
    pipeline into the classic synchronous round — the r07/r08 behavior,
    kept reachable as this A/B's baseline). Both runs use the same fixed
    chunk so the comparison isolates the routing, not the chunking.

    The default shape is 1k nodes / 6k pods, NOT the 5k/30k headline:
    with gangs interleaved into every chunk, flush mode runs the WHOLE
    mixed stream through the classic path — per-chunk AffinityData
    rebuilds plus the full-label-axis strict scan, the costs
    PROFILE_r08 measured at >3,500 s (timed out) on the headline shape.
    The baseline must finish for the ratio to be a measurement.
    Asserts the hard invariant: ZERO partially bound gangs in either
    mode."""
    import gc

    from kubernetes_tpu.engine.gang import GANG_NAME_ANNOTATION
    from kubernetes_tpu.utils.trace import COUNTERS

    chunk = int(os.environ.get("BENCH_GANG_CHUNK", "1024"))

    def drain(gang_pipeline: bool):
        api, sched = build(n_nodes, n_pods, "gang_mix")
        sched.gang_pipeline = gang_pipeline
        t0 = time.monotonic()
        totals = sched.run_until_drained(max_batch=chunk)
        elapsed = time.monotonic() - t0
        by_gang = {}
        for p in api.list("Pod")[0]:
            g = p.annotations.get(GANG_NAME_ANNOTATION)
            if g is not None:
                by_gang.setdefault(g, []).append(bool(p.node_name))
        partial = sum(1 for flags in by_gang.values()
                      if len(set(flags)) != 1)
        return totals, elapsed, partial

    if warmup:
        # warm BOTH modes: the flush baseline must not be charged for
        # cold XLA compiles the pipelined run already amortized
        drain(True)
        drain(False)
    gc.collect()
    gc.freeze()
    gc.disable()
    COUNTERS.reset()
    try:
        totals, elapsed, partial = drain(True)
        snap = COUNTERS.snapshot()
        _totals_f, elapsed_flush, partial_flush = drain(False)
    finally:
        gc.enable()
        gc.unfreeze()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    # the hard invariant, enforced loudly: a partially bound gang is a
    # broken atomicity contract, not a perf data point — refuse to report
    # numbers over it (same spirit as the lint gate; explicit raise, not
    # a bare assert, so python -O cannot silently drop the check)
    if partial or partial_flush:
        raise RuntimeError(f"partially bound gangs: pipelined={partial} "
                           f"flush={partial_flush}")
    return {
        "gangmix_pods_s": round(totals["bound"] / elapsed, 1)
        if elapsed > 0 else 0.0,
        "gangmix_elapsed_s": round(elapsed, 3),
        "gangmix_bound": totals["bound"],
        "gangmix_unschedulable": totals["unschedulable"],
        "gangmix_partial_gangs": partial + partial_flush,  # 0 by the
        # raise above — kept in the JSON so trajectory readers see the
        # invariant was measured, not assumed
        "gangmix_chunk": chunk,
        # the A/B this scenario exists for: same drain with every
        # gang-bearing chunk flushing the pipeline (the old routing)
        "gangmix_flush_elapsed_s": round(elapsed_flush, 3),
        "gangmix_speedup_vs_flush": round(elapsed_flush / elapsed, 2)
        if elapsed > 0 else 0.0,
        # routing observability (ISSUE 5): gangs dispatched wave-granular,
        # gangs atomically rolled back at the fence, fence requeues
        "gangmix_gang_wave_dispatch": cnt("engine.gang_wave_dispatch"),
        "gangmix_gang_fence_rollbacks": cnt("engine.gang_fence_rollbacks"),
        "gangmix_gang_requeued": totals.get("gang_requeued", 0),
        "gangmix_fence_requeued": totals.get("fence_requeued", 0),
        "gangmix_wave_dispatch": cnt("engine.wave_dispatch"),
    }


def lint_gate_or_die():
    """`--lint-gate` / BENCH_LINT_GATE=1: refuse to report perf numbers
    from a tree carrying unsuppressed graftlint hazards. A number measured
    over an aliasing upload or a hidden host sync is not a number — it is
    either racing (wrong placements under load) or quietly serialized
    (wrong overlap). Pure AST, milliseconds, no device."""
    import sys

    from kubernetes_tpu.analysis.lint import lint_gate
    ok, report = lint_gate()
    if not ok:
        print(report, file=sys.stderr)
        print(json.dumps({"metric": "schedule_pods_per_sec", "value": 0,
                          "unit": "pods/s", "error": "lint-gate: tree has "
                          "unsuppressed graftlint findings"}))
        raise SystemExit(3)


def main():
    import sys
    if "--lint-gate" in sys.argv[1:] \
            or os.environ.get("BENCH_LINT_GATE", "0") == "1":
        lint_gate_or_die()
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")
    warmup = os.environ.get("BENCH_WARMUP", "1") != "0"

    def attempt():
        # warmup (compile at identical shapes) INSIDE the retry scope: a
        # transient remote-compile failure during warmup must not zero the
        # round (it did in r02)
        if warmup:
            run_once(n_nodes, n_pods, profile)
        # quiesce the collector for the measured run: a gen-2 GC pass over a
        # heap holding 30k pods + 5k nodes costs 200-400ms of pure pause —
        # the standard CPython service tuning (freeze the warm heap, collect
        # nothing during the burst, restore after)
        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            return run_once(n_nodes, n_pods, profile)
        finally:
            gc.enable()
            gc.unfreeze()

    try:
        totals, elapsed, sched = attempt()
    except Exception as e:  # tunneled-TPU transport flakes are transient;
        # one retry so a single dropped RPC doesn't zero the round's number
        import sys
        print(f"bench: retrying after transient error: {e}", file=sys.stderr)
        totals, elapsed, sched = attempt()

    # extender wire latency on the same hardware (skippable for quick
    # local smokes; the driver's run keeps it on)
    ext_p50 = ext_p99 = None
    if os.environ.get("BENCH_EXTENDER", "1") != "0":
        try:
            ext_p50, ext_p99 = measure_extender_latency(n_nodes)
        except Exception as e:
            import sys
            print(f"bench: extender measurement failed: {e}",
                  file=sys.stderr)

    # compat-mode scheduleOne-over-HTTP throughput (the reference protocol
    # driven end to end; BENCH_COMPAT=0 to skip)
    compat = None
    if os.environ.get("BENCH_COMPAT", "1") != "0":
        try:
            compat = measure_compat_scheduleone(
                n_nodes,
                n_pods=int(os.environ.get("BENCH_COMPAT_PODS", 2000)),
                drivers=int(os.environ.get("BENCH_COMPAT_DRIVERS", 8)))
        except Exception as e:
            import sys
            print(f"bench: compat measurement failed: {e}", file=sys.stderr)

    # arrival-stream scenario: rate-driven creates, per-interval sustained
    # throughput, meaningful create->bound percentiles (BENCH_ARRIVAL=0 to
    # skip)
    arrival = None
    arrival_rate = float(os.environ.get("BENCH_ARRIVAL_RATE", 5000))
    if os.environ.get("BENCH_ARRIVAL", "1") != "0":
        try:
            arrival = run_arrival(
                n_nodes, rate=arrival_rate,
                duration_s=float(os.environ.get("BENCH_ARRIVAL_SECONDS", 6)),
                profile=profile if profile in ("density", "binpack")
                else "density")
        except Exception as e:
            import sys
            print(f"bench: arrival measurement failed: {e}", file=sys.stderr)

    # mixed-affinity drain (ISSUE 3 headline): same box, same protocol,
    # >=15% required (anti-)affinity pods (BENCH_MIXED=0 to skip)
    mixed = None
    if os.environ.get("BENCH_MIXED", "1") != "0":
        try:
            mixed = measure_mixed_affinity(
                n_nodes, int(os.environ.get("BENCH_MIXED_PODS", n_pods)),
                warmup=warmup)
        except Exception as e:
            import sys
            print(f"bench: mixed-affinity measurement failed: {e}",
                  file=sys.stderr)

    # gang-heavy drain (ISSUE 5): gangs on the pipeline vs the
    # flush-every-gang baseline, same box, same chunk (BENCH_GANGMIX=0 to
    # skip)
    gangmix = None
    if os.environ.get("BENCH_GANGMIX", "1") != "0":
        try:
            gangmix = measure_gang_mix(
                int(os.environ.get("BENCH_GANGMIX_NODES", 1000)),
                int(os.environ.get("BENCH_GANGMIX_PODS", 6000)),
                warmup=warmup)
        except Exception as e:
            import sys
            print(f"bench: gang-mix measurement failed: {e}",
                  file=sys.stderr)

    bound = totals["bound"]
    pods_per_s = bound / elapsed if elapsed > 0 else 0.0
    c2b = sched.metrics.create_to_bound  # honest per-pod distribution:
    # first-seen-unscheduled -> bind-complete, queue wait included
    out = dict({
        "metric": f"pods scheduled/sec ({profile}, {n_nodes} nodes, {n_pods} pods, create->bound)",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_s / 100.0, 2),
        "elapsed_s": round(elapsed, 3),
        "bound": bound,
        "unschedulable": totals["unschedulable"],
        "p50_create_to_bound_ms": round(c2b.percentile(50) * 1e3, 3),
        "p99_create_to_bound_ms": round(c2b.percentile(99) * 1e3, 3),
        # pop -> bind-complete span per pod (scheduler.go:289 semantics)
        "p99_e2e_ms": round(sched.metrics.e2e_latency.percentile(99) * 1e3, 3),
        # HTTP /filter+/prioritize round at n_nodes vs the 5s extender
        # budget (core/extender.go:36), measured on this hardware
        "extender_p50_ms": round(ext_p50, 3) if ext_p50 is not None else None,
        "extender_p99_ms": round(ext_p99, 3) if ext_p99 is not None else None,
        # compat mode: scheduleOne loops over real HTTP (filter with full
        # NodeNames, prioritize over survivors, bind) — sustained pods/s
        # through the reference's own protocol
        "compat_pods_s": round(compat[0], 1) if compat else None,
        "compat_p50_ms": round(compat[1], 3) if compat and compat[1] else None,
        "compat_p99_ms": round(compat[2], 3) if compat and compat[2] else None,
        "compat_bound": compat[3] if compat else None,
        "compat_unschedulable": compat[4] if compat else None,
        # arrival stream: rate-driven creates; sustained = median 1s-interval
        # bound count; offered vs sustained vs backlog reported TOGETHER so
        # a host-bound run can't silently read as keeping up (ISSUE 2);
        # create->bound percentiles are per-pod and non-degenerate
        "arrival_offered_pods_s": arrival["offered_pods_s"]
        if arrival else None,
        "arrival_sustained_pods_s": arrival["sustained_pods_s"]
        if arrival else None,
        "arrival_backlog_at_offer_end": arrival["backlog_at_offer_end"]
        if arrival else None,
        "arrival_unbound": arrival["unbound"] if arrival else None,
        "arrival_intervals": arrival["intervals"] if arrival else None,
        "arrival_p50_create_to_bound_ms": round(arrival["p50_ms"], 3)
        if arrival else None,
        "arrival_p99_create_to_bound_ms": round(arrival["p99_ms"], 3)
        if arrival else None,
        "arrival_bound": arrival["bound"] if arrival else None,
    }, **(mixed or {}), **(gangmix or {}))
    print(json.dumps(out))

    # resume the bench trajectory (ISSUE 5 satellite): persist this round's
    # numbers as the BENCH_r09 artifact — same {cmd, rc, parsed} shape as
    # the driver-written BENCH_r01..r05 files, so trajectory readers keep
    # working. BENCH_ARTIFACT= (empty) disables, or names another round.
    artifact = os.environ.get("BENCH_ARTIFACT", "BENCH_r09.json")
    if artifact:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            artifact)
        try:
            with open(path, "w") as f:
                json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                           "parsed": out}, f, indent=2)
                f.write("\n")
        except OSError as e:
            import sys
            print(f"bench: artifact write failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
