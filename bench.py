"""Headline benchmark: batch-place the pending queue on a hollow cluster.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

Scenario (north star, BASELINE.md): 30,000 pending pods onto a 5,000-node
hollow cluster, end-to-end through the control plane — apiserver-lite create,
watch-driven queue fill, tensor snapshot, fused TPU batch placement with
sequential assume semantics, per-pod bind writes, watch confirmation.

vs_baseline is the ratio against the reference's 100 pods/s warn-level
scheduler throughput (test/integration/scheduler_perf/scheduler_test.go:35 —
the hard floor is 30 pods/s; real 1.7-era deployments sat between the two).

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_PROFILE (density|binpack|affinity|
hetero), BENCH_WARMUP=0 to skip the compile-warming run.
"""

from __future__ import annotations

import json
import os
import time

# persistent XLA compilation cache: a flaky remote-compile service mid-round
# costs one retry, not the round (r02 lost its number to a warmup-time
# connection refusal). Set before any jax import traces a kernel.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
try:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def build(n_nodes: int, n_pods: int, profile: str):
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + n_pods)))
    nodes = hollow_nodes(n_nodes, heterogeneous=(profile == "hetero"),
                         gpu_fraction=0.3 if profile == "hetero" else 0.0,
                         taint_fraction=0.1 if profile == "hetero" else 0.0)
    pods = PROFILES[profile](n_pods)
    load_cluster(api, nodes, pods)
    sched = Scheduler(api, record_events=False)
    sched.start()
    return api, sched


def run_once(n_nodes: int, n_pods: int, profile: str):
    api, sched = build(n_nodes, n_pods, profile)
    t0 = time.monotonic()
    totals = sched.run_until_drained()
    elapsed = time.monotonic() - t0
    return totals, elapsed, sched


def measure_extender_latency(n_nodes: int, rounds: int = 20):
    """Real HTTP /filter + /prioritize latency against the TPU backend at
    n_nodes (the 5s extender budget of core/extender.go:36, measured on
    hardware instead of asserted structurally — r4 VERDICT weak #5).
    Returns (p50_ms, p99_ms)."""
    import http.client
    import time as _time

    from kubernetes_tpu.api import serde
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )

    backend = TPUExtenderBackend()
    nodes = hollow_nodes(n_nodes)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 16}"
    backend.sync_nodes(nodes)
    # warm in-process BEFORE serving: the first evaluation pays snapshot
    # build + kernel compile, which must not burn an HTTP timeout
    backend.filter(make_pod("warm", cpu=100, memory=256 << 20), None, None)
    backend.prioritize(make_pod("warm2", cpu=100, memory=256 << 20),
                       None, None)
    srv = ExtenderHTTPServer(backend, prefix="/scheduler")
    srv.start()
    try:
        lat = []
        for i in range(rounds + 3):
            pod = make_pod(f"ext-{i}", cpu=100, memory=256 << 20)
            body = json.dumps({"Pod": serde.encode_pod(pod),
                               "NodeNames": None, "Nodes": None})
            t0 = _time.perf_counter()
            for verb in ("filter", "prioritize"):
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=30)
                conn.request("POST", f"/scheduler/{verb}", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200
                conn.close()
            if i >= 3:  # first calls pay snapshot build + compile
                lat.append(_time.perf_counter() - t0)
        lat.sort()
        return (lat[len(lat) // 2] * 1e3,
                lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3)
    finally:
        srv.stop()


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 30000))
    profile = os.environ.get("BENCH_PROFILE", "density")
    warmup = os.environ.get("BENCH_WARMUP", "1") != "0"

    def attempt():
        # warmup (compile at identical shapes) INSIDE the retry scope: a
        # transient remote-compile failure during warmup must not zero the
        # round (it did in r02)
        if warmup:
            run_once(n_nodes, n_pods, profile)
        # quiesce the collector for the measured run: a gen-2 GC pass over a
        # heap holding 30k pods + 5k nodes costs 200-400ms of pure pause —
        # the standard CPython service tuning (freeze the warm heap, collect
        # nothing during the burst, restore after)
        import gc
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            return run_once(n_nodes, n_pods, profile)
        finally:
            gc.enable()
            gc.unfreeze()

    try:
        totals, elapsed, sched = attempt()
    except Exception as e:  # tunneled-TPU transport flakes are transient;
        # one retry so a single dropped RPC doesn't zero the round's number
        import sys
        print(f"bench: retrying after transient error: {e}", file=sys.stderr)
        totals, elapsed, sched = attempt()

    # extender wire latency on the same hardware (skippable for quick
    # local smokes; the driver's run keeps it on)
    ext_p50 = ext_p99 = None
    if os.environ.get("BENCH_EXTENDER", "1") != "0":
        try:
            ext_p50, ext_p99 = measure_extender_latency(n_nodes)
        except Exception as e:
            import sys
            print(f"bench: extender measurement failed: {e}",
                  file=sys.stderr)

    bound = totals["bound"]
    pods_per_s = bound / elapsed if elapsed > 0 else 0.0
    c2b = sched.metrics.create_to_bound  # honest per-pod distribution:
    # first-seen-unscheduled -> bind-complete, queue wait included
    print(json.dumps({
        "metric": f"pods scheduled/sec ({profile}, {n_nodes} nodes, {n_pods} pods, create->bound)",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_s / 100.0, 2),
        "elapsed_s": round(elapsed, 3),
        "bound": bound,
        "unschedulable": totals["unschedulable"],
        "p50_create_to_bound_ms": round(c2b.percentile(50) * 1e3, 3),
        "p99_create_to_bound_ms": round(c2b.percentile(99) * 1e3, 3),
        # pop -> bind-complete span per pod (scheduler.go:289 semantics)
        "p99_e2e_ms": round(sched.metrics.e2e_latency.percentile(99) * 1e3, 3),
        # HTTP /filter+/prioritize round at n_nodes vs the 5s extender
        # budget (core/extender.go:36), measured on this hardware
        "extender_p50_ms": round(ext_p50, 3) if ext_p50 is not None else None,
        "extender_p99_ms": round(ext_p99, 3) if ext_p99 is not None else None,
    }))


if __name__ == "__main__":
    main()
