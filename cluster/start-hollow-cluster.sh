#!/usr/bin/env bash
# Start a hollow cluster with two competing scheduler daemons — the rig's
# analog of the reference's cluster/ provisioning + kubemark start scripts
# (test/kubemark/start-kubemark.sh): everything in one process, sized by
# env, exiting non-zero if the storm does not fully bind.
#
#   NUM_NODES=100 NUM_PODS=2000 ./cluster/start-hollow-cluster.sh
set -euo pipefail
cd "$(dirname "$0")/.."

NUM_NODES="${NUM_NODES:-50}"
NUM_PODS="${NUM_PODS:-500}"
POLICY="${POLICY_CONFIG_FILE:-}"

args=(--nodes "$NUM_NODES" --pods "$NUM_PODS")
if [[ -n "$POLICY" ]]; then
  args+=(--policy-config-file "$POLICY")
fi

out="$(python -m kubernetes_tpu.server.daemon "${args[@]}")"
echo "$out"
[[ "$out" == *"bound=${NUM_PODS}/${NUM_PODS}"* ]]
